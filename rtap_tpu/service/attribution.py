"""Per-alert provenance: which encoder fields drove an anomaly alert.

The paper's premise is alerts that arrive *before* failure; an operator
acting on one needs to know WHICH of a node's fused metrics (cpu? mem?
net?) moved. SDR semantics make that decodable ("Properties of Sparse
Distributed Representations" / "Encoding Data for HTM Systems",
PAPERS.md): each field owns a disjoint encoder bit range, the RDSE maps
value -> bucket ``b`` -> bits ``{hash(b + k) : k < w}``, and buckets
``b0``, ``b1`` share exactly ``max(0, w - |b1 - b0|)`` hash keys — SDR
overlap decays linearly with bucket distance, BY CONSTRUCTION. So a
field whose consecutive-tick encodings stopped overlapping is a field
whose representation jumped, and the anomalous columns (active but
unpredicted) inherit that novelty through their field-segment potential
pools.

:class:`AlertAttributor` decodes in this encoder key-space: per alerting
stream it compares the current tick's per-field bucket against the
previous tick's, converts bucket distance to lost-overlap fraction
``min(1, |Δbucket| / w)``, and reports the top-k fields by normalized
contribution. The offset term of the bucket map cancels in the
difference, so no per-stream encoder state needs fetching from the
device — attribution costs one O(n_fields) numpy pass per ALERTING
stream plus one per-group history copy per tick, and is exact in
key-space (the per-tick column masks never reach the host from the
chunked device scan, so column-level decoding post-hoc is not possible
without changing the compiled step; the key-space decode is the same
overlap those columns see).

Enabled by ``serve --alert-attribution``; alert JSONL lines gain
``"top_fields": [{"field": i, "contribution": c, "bucket_delta": d},
...]`` (empty list on the first tick a stream is seen, or when nothing
moved — e.g. a purely temporal/date-driven anomaly).
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.models.oracle.encoders import rdse_bucket, scalar_bucket

__all__ = ["AlertAttributor"]

#: LRU bound on tracked routing tuples. Sized an order of magnitude
#: above any feasible live fleet — the serving shapes top out at ~100
#: groups (100k streams at G=1024) and the compiler wall caps streams
#: per chip well before 8192 groups — so in practice only RETIRED
#: tuples (membership-rebuild churn) are ever evicted; a fleet that
#: somehow exceeds the cap degrades to empty top_fields and counts it
#: in ``live_evictions`` instead of hiding it.
_MAX_TRACKED_ROUTES = 8192


class AlertAttributor:
    """Stateful per-field novelty decoder for alert provenance.

    One instance serves the whole loop: history is keyed by the emission
    routing's id tuple (one entry per group; rebuilt snapshots age out),
    and the previous-value row carries the last FINITE value per field —
    a missing sample must not erase the baseline the next real value is
    judged against.
    """

    def __init__(self, cfg: ModelConfig, top_k: int = 3):
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1; got {top_k}")
        self.cfg = cfg
        self.top_k = int(top_k)
        if cfg.scalar is not None:
            self._w = int(cfg.scalar.width)
        else:
            self._w = int(cfg.rdse.active_bits)
            # same f32 rounding as the encoder's own resolution path
            self._res = float(np.float32(cfg.rdse.resolution))
        self._prev: dict[tuple, tuple[np.ndarray, int]] = {}
        self._calls = 0
        #: evictions of recently-updated (plausibly live) routes — stays
        #: 0 unless the fleet exceeds _MAX_TRACKED_ROUTES groups
        self.live_evictions = 0

    def _bucket_delta(self, cur: np.ndarray, base: np.ndarray) -> np.ndarray:
        """Per-field bucket distance between two value rows.

        RDSE: computed directly as round((cur - base)/res) — subtracting
        FIRST is what makes the offset cancel exactly AND keeps f32
        precision (round(cur/res) - round(base/res) loses small moves on
        large-magnitude baselines and saturates at the ±2^30 bucket
        clamp, zeroing the attribution of the very field that spiked).
        Scalar encoder: bucket difference after the range clip (the
        clipped domain is small by construction)."""
        if self.cfg.scalar is not None:
            return (scalar_bucket(cur, self.cfg.scalar)
                    - scalar_bucket(base, self.cfg.scalar))
        return rdse_bucket(cur, base, self._res)

    def update_and_attribute(self, stream_ids: list[str],
                             values: np.ndarray,
                             alert_idx: np.ndarray) -> dict[int, list[dict]]:
        """Advance per-stream history one tick; attribute the alerts.

        `values` is the emission batch's value block ([n] or
        [n, n_fields], aligned with `stream_ids`); `alert_idx` the
        indices whose alert fired. Returns {index: top_fields list}.
        """
        self._calls += 1
        vals = np.asarray(values, np.float32)
        if vals.ndim == 1:
            vals = vals[:, None]
        key = tuple(stream_ids)
        entry = self._prev.get(key)
        prev = entry[0] if entry is not None else None
        if prev is not None and prev.shape != vals.shape:
            prev = None  # field-shape change: restart history
        # carry the last finite value forward per field: NaN gaps keep
        # the pre-gap baseline (the encoder's missing-sample semantics)
        if prev is None:
            carried = vals.copy()
        else:
            carried = np.where(np.isfinite(vals), vals, prev)
        self._prev[key] = (carried, self._calls)
        if len(self._prev) > _MAX_TRACKED_ROUTES:
            # LRU prune (rare: only route churn beyond the cap reaches
            # here). An evicted entry updated within the last cap-worth
            # of calls was plausibly a LIVE group's — count it loudly.
            items = sorted(self._prev.items(), key=lambda kv: kv[1][1])
            drop = items[: len(items) - _MAX_TRACKED_ROUTES]
            floor = self._calls - _MAX_TRACKED_ROUTES
            self.live_evictions += sum(1 for _, v in drop if v[1] >= floor)
            self._prev = dict(items[len(drop):])
        out: dict[int, list[dict]] = {}
        for g in np.asarray(alert_idx).ravel():
            g = int(g)
            if prev is None:
                out[g] = []
                continue
            cur, base = vals[g], prev[g]
            finite = np.isfinite(cur) & np.isfinite(base)
            db = np.zeros(cur.shape[0], np.int64)
            if finite.any():
                db[finite] = self._bucket_delta(cur[finite], base[finite])
            novelty = np.minimum(np.abs(db), self._w) / float(self._w)
            total = float(novelty.sum())
            if total <= 0.0:
                out[g] = []
                continue
            order = np.argsort(-novelty, kind="stable")[: self.top_k]
            out[g] = [
                {"field": int(f),
                 "contribution": round(float(novelty[f] / total), 4),
                 "bucket_delta": int(db[f])}
                for f in order if novelty[f] > 0.0
            ]
        return out
