"""Per-alert provenance: which encoder fields drove an anomaly alert.

The paper's premise is alerts that arrive *before* failure; an operator
acting on one needs to know WHICH of a node's fused metrics (cpu? mem?
net?) moved. SDR semantics make that decodable ("Properties of Sparse
Distributed Representations" / "Encoding Data for HTM Systems",
PAPERS.md): each field owns a disjoint encoder bit range, the RDSE maps
value -> bucket ``b`` -> bits ``{hash(b + k) : k < w}``, and buckets
``b0``, ``b1`` share exactly ``max(0, w - |b1 - b0|)`` hash keys — SDR
overlap decays linearly with bucket distance, BY CONSTRUCTION. So a
field whose consecutive-tick encodings stopped overlapping is a field
whose representation jumped, and the anomalous columns (active but
unpredicted) inherit that novelty through their field-segment potential
pools.

:class:`AlertAttributor` decodes in this encoder key-space: per alerting
stream it compares the current tick's per-field bucket against the
previous tick's, converts bucket distance to lost-overlap fraction
``min(1, |Δbucket| / w)``, and reports the top-k fields by normalized
contribution. The offset term of the bucket map cancels in the
difference, so no per-stream encoder state needs fetching from the
device — attribution costs one O(n_fields) numpy pass per ALERTING
stream plus one per-group history copy per tick, and is exact in
key-space (the per-tick column masks never reach the host from the
chunked device scan, so column-level decoding post-hoc is not possible
without changing the compiled step; the key-space decode is the same
overlap those columns see).

Enabled by ``serve --alert-attribution``; alert JSONL lines gain
``"top_fields": [{"field": i, "contribution": c, "bucket_delta": d},
...]`` (empty list on the first tick a stream is seen, or when nothing
moved — e.g. a purely temporal/date-driven anomaly).
"""

from __future__ import annotations

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.models.oracle.encoders import rdse_bucket, scalar_bucket

__all__ = ["AlertAttributor"]

#: LRU bound on tracked routing tuples. Sized an order of magnitude
#: above any feasible live fleet — the serving shapes top out at ~100
#: groups (100k streams at G=1024) and the compiler wall caps streams
#: per chip well before 8192 groups — so in practice only RETIRED
#: tuples (membership-rebuild churn) are ever evicted; a fleet that
#: somehow exceeds the cap degrades to empty top_fields and counts it
#: in ``live_evictions`` instead of hiding it.
_MAX_TRACKED_ROUTES = 8192


class AlertAttributor:
    """Stateful per-field novelty decoder for alert provenance.

    One instance serves the whole loop: history is keyed by the emission
    routing's id tuple (one entry per group; rebuilt snapshots age out),
    and the previous-value row carries the last FINITE value per field —
    a missing sample must not erase the baseline the next real value is
    judged against.
    """

    def __init__(self, cfg: ModelConfig, top_k: int = 3):
        if top_k < 1:
            raise ValueError(f"top_k must be >= 1; got {top_k}")
        self.cfg = cfg
        self.top_k = int(top_k)
        if cfg.composite is not None:
            # composite family (ISSUE 9): per-field kinds and geometry;
            # alerts name the spiked FIELD by its declared name. Delta
            # fields compare consecutive ENCODED deltas, which needs a
            # 2-deep value history (base and base2 below).
            self._names = [name for name, _k, _o, _s in cfg.field_layout()]
            self._kinds = [f.kind for f in cfg.composite.fields]
            self._ws = np.array([f.active_bits for f in cfg.composite.fields],
                                np.int64)
            self._ress = np.array(
                [np.float32(r) for r in cfg.field_resolutions()], np.float32)
            self._cclamps = np.array(
                [f.categorical_clamp() for f in cfg.composite.fields],
                np.int64)
            self._w = int(self._ws.max())  # uniform-path fields unused
        elif cfg.scalar is not None:
            self._w = int(cfg.scalar.width)
        else:
            self._w = int(cfg.rdse.active_bits)
            # same f32 rounding as the encoder's own resolution path
            self._res = float(np.float32(cfg.rdse.resolution))
        self._prev: dict[tuple, tuple] = {}
        self._calls = 0
        #: evictions of recently-updated (plausibly live) routes — stays
        #: 0 unless the fleet exceeds _MAX_TRACKED_ROUTES groups
        self.live_evictions = 0

    def _bucket_delta(self, cur: np.ndarray, base: np.ndarray) -> np.ndarray:
        """Per-field bucket distance between two value rows.

        RDSE: computed directly as round((cur - base)/res) — subtracting
        FIRST is what makes the offset cancel exactly AND keeps f32
        precision (round(cur/res) - round(base/res) loses small moves on
        large-magnitude baselines and saturates at the ±2^30 bucket
        clamp, zeroing the attribution of the very field that spiked).
        Scalar encoder: bucket difference after the range clip (the
        clipped domain is small by construction)."""
        if self.cfg.scalar is not None:
            return (scalar_bucket(cur, self.cfg.scalar)
                    - scalar_bucket(base, self.cfg.scalar))
        return rdse_bucket(cur, base, self._res)

    def update_and_attribute(self, stream_ids: list[str],
                             values: np.ndarray,
                             alert_idx: np.ndarray) -> dict[int, list[dict]]:
        """Advance per-stream history one tick; attribute the alerts.

        `values` is the emission batch's value block ([n] or
        [n, n_fields], aligned with `stream_ids`); `alert_idx` the
        indices whose alert fired. Returns {index: top_fields list}.
        """
        self._calls += 1
        composite = self.cfg.composite is not None
        vals = np.asarray(values, np.float32)
        if vals.ndim == 1:
            vals = vals[:, None]
        key = tuple(stream_ids)
        entry = self._prev.get(key)
        prev = entry[0] if entry is not None else None
        prev2 = entry[1] if (composite and entry is not None) else None
        if prev is not None and prev.shape != vals.shape:
            prev = prev2 = None  # field-shape change: restart history
        # carry the last finite value forward per field: NaN gaps keep
        # the pre-gap baseline (the encoder's missing-sample semantics)
        if prev is None:
            carried = vals.copy()
        else:
            carried = np.where(np.isfinite(vals), vals, prev)
        # composite keeps 2-deep history (delta fields compare consecutive
        # ENCODED deltas, which needs the tick-before-base row too); the
        # last tuple element is always the LRU clock
        self._prev[key] = (carried, prev, self._calls) if composite \
            else (carried, self._calls)
        if len(self._prev) > _MAX_TRACKED_ROUTES:
            # LRU prune (rare: only route churn beyond the cap reaches
            # here). An evicted entry updated within the last cap-worth
            # of calls was plausibly a LIVE group's — count it loudly.
            items = sorted(self._prev.items(), key=lambda kv: kv[1][-1])
            drop = items[: len(items) - _MAX_TRACKED_ROUTES]
            floor = self._calls - _MAX_TRACKED_ROUTES
            self.live_evictions += sum(1 for _, v in drop if v[-1] >= floor)
            self._prev = dict(items[len(drop):])
        out: dict[int, list[dict]] = {}
        for g in np.asarray(alert_idx).ravel():
            g = int(g)
            if prev is None:
                out[g] = []
                continue
            cur, base = vals[g], prev[g]
            if composite:
                base2 = prev2[g] if prev2 is not None else None
                db, novelty = self._composite_novelty(cur, base, base2)
                ws = self._ws
            else:
                finite = np.isfinite(cur) & np.isfinite(base)
                db = np.zeros(cur.shape[0], np.int64)
                if finite.any():
                    db[finite] = self._bucket_delta(cur[finite], base[finite])
                novelty = np.minimum(np.abs(db), self._w) / float(self._w)
                ws = None
            total = float(novelty.sum())
            if total <= 0.0:
                out[g] = []
                continue
            order = np.argsort(-novelty, kind="stable")[: self.top_k]
            out[g] = [
                {"field": int(f),
                 # composite alerts name the spiked FIELD, not just its
                 # wire dimension — the operator-facing half of the
                 # ISSUE 9 decode generalization
                 **({"name": self._names[int(f)]} if composite else {}),
                 "contribution": round(float(novelty[f] / total), 4),
                 "bucket_delta": int(db[f])}
                for f in order if novelty[f] > 0.0
            ]
        return out

    def _composite_novelty(self, cur: np.ndarray, base: np.ndarray,
                           base2: np.ndarray | None):
        """Per-field (bucket_delta, lost-overlap novelty) for a composite
        config: rdse fields decode exactly like the uniform family (at
        their own resolution/width); CATEGORICAL fields are all-or-
        nothing (distinct ids share no hash keys, so any id change is
        full novelty); DELTA fields compare this tick's encoded first
        difference against the previous tick's — which needs the
        2-deep history (no base2 yet -> no verdict for that field)."""
        F = len(self._kinds)
        db = np.zeros(F, np.int64)
        nov = np.zeros(F, np.float64)
        for f, kind in enumerate(self._kinds):
            c, b = float(cur[f]), float(base[f])
            if not (np.isfinite(c) and np.isfinite(b)):
                continue
            w = int(self._ws[f])
            res = float(self._ress[f])
            if kind == "categorical":
                # decode through the ENCODER's id clamp: two ids that
                # clip to the same category produce bit-identical SDRs
                # (categorical_bits), so they must not attribute as a
                # field change
                clamp = int(self._cclamps[f])
                cc = min(max(int(rdse_bucket(c, 0.0, res)), -clamp), clamp)
                bb = min(max(int(rdse_bucket(b, 0.0, res)), -clamp), clamp)
                d = cc - bb
                db[f] = d
                nov[f] = 1.0 if d else 0.0
            elif kind == "delta":
                if base2 is None or not np.isfinite(base2[f]):
                    continue
                d_cur = float(np.float32(c) - np.float32(b))
                d_prev = float(np.float32(b) - np.float32(base2[f]))
                # subtract-first like the rdse path: the shared baseline
                # term cancels exactly in f32
                db[f] = int(rdse_bucket(d_cur, d_prev, res))
                nov[f] = min(abs(int(db[f])), w) / float(w)
            else:  # rdse
                db[f] = int(rdse_bucket(c, b, res))
                nov[f] = min(abs(int(db[f])), w) / float(w)
        return db, nov
