"""AOT warm-up: compile every knowable serve program BEFORE tick 0.

The 1-hour 100k-stream soak (reports/live_soak_100k_1h.json) missed 9 of
3600 deadlines with latency_max 7.38 s — every one a warm-up compile
landing INSIDE a scored tick (the chunk_stagger ramp-in dispatches chunk
lengths 1..M, each a distinct XLA program, and the old warm-up keying only
serialized them). The program set is fully knowable at serve start:

  chunk lengths   1..micro_chunk (steady-state flushes at M; boundary
                  aligns, ramp-in, membership changes and the final tick
                  flush every partial length below it)
  configs         one per distinct group ModelConfig (stagger_learn gives
                  groups distinct learn_phase fields -> distinct programs)
  learn flags     the serve learn flag, plus learn=False when a
                  degradation ladder can flip scoring to frozen mid-run
  claim program   set_state_row (first dynamic slot claim / restore
                  realignment), when claimable capacity exists

so this module compiles all of them up front against a THROWAWAY state and
the loop starts with a fully warm cache; no compile can occur inside a
scored tick.

Mechanism note: jax.jit(...).lower(...).compile() builds the executable
but does NOT seed the jit dispatch cache (verified on this jax: a later
call re-traces), so warming EXECUTES each program once on scratch state —
that is the only path that guarantees the serve-loop call hits a warm
cache. The scratch state is donated through the same entry points the loop
uses (ops/step.chunk_step, ops/step.set_state_row) and freed afterwards;
group state, likelihood moments and telemetry are untouched.

Exposed metric: rtap_obs_aot_programs_compiled_total (docs/TELEMETRY.md).
Integration test: tests/integration/test_aot_serve.py pins "zero cold
compiles after tick 0" via the jit cache sizes themselves.
"""

from __future__ import annotations

from rtap_tpu.obs import get_registry


def knowable_programs(groups, micro_chunk: int, learn: bool,
                      degradation=None) -> list[tuple]:
    """The (chunk length m, group config, learn flag) programs a serve
    loop with these parameters can ever dispatch — the same keying
    live_loop's warm-up set uses, enumerated instead of discovered."""
    learn_flags = {bool(learn)}
    if degradation is not None and learn:
        # the ladder's score_only step (level >= 2) dispatches learn=False
        learn_flags.add(False)
    cfgs = []
    for g in groups:
        if g.cfg not in cfgs:
            cfgs.append(g.cfg)
    return [
        (m, cfg, lf)
        for cfg in cfgs
        for m in range(1, max(1, int(micro_chunk)) + 1)
        for lf in sorted(learn_flags)
    ]


def prewarm(groups, micro_chunk: int, learn: bool, degradation=None,
            include_claim: bool = False, seed: int = 0) -> set[tuple]:
    """Compile-and-execute every knowable program on throwaway state.

    Returns the warmed key set ((m, config, learn) — live_loop seeds its
    single-flight `warmed` set with it so its own bookkeeping agrees).
    CPU-backend groups have no device programs; meshed groups compile per
    (mesh, shapes) inside sharded_chunk_step's own cache and are warmed by
    their first real dispatch — both are skipped here (the mesh path's
    fleet shapes make scratch-state warm-up a deliberate non-goal until a
    soak shows it missing deadlines).
    """
    device_groups = [g for g in groups
                     if getattr(g, "backend", None) == "tpu"
                     and getattr(g, "mesh", None) is None]
    if not device_groups:
        return set()
    import jax.numpy as jnp
    import numpy as np

    from rtap_tpu.models.state import init_state
    from rtap_tpu.ops.step import (
        chunk_step, replicate_state_device, set_state_row,
    )

    counter = get_registry().counter(
        "rtap_obs_aot_programs_compiled_total",
        "serve programs compiled-or-warmed ahead of tick 0 by the AOT "
        "warm-up (chunk lengths x group configs x learn flags, + claim "
        "programs; a re-warm of an already-cached program counts — the "
        "metric tracks warm-up passes, the jit cache dedupes compiles)")
    programs = knowable_programs(device_groups, micro_chunk, learn, degradation)
    warmed: set[tuple] = set()
    by_cfg: dict = {}
    for m, cfg, lf in programs:
        by_cfg.setdefault(cfg, []).append((m, lf))
    # health reducers are a static flag of the compiled program (ISSUE 6):
    # warm the variant the groups will actually dispatch, or the warm-up
    # compiles a program the loop never uses and pays the real compile
    # inside a scored tick
    health_by_cfg = {
        cfg: any(getattr(g, "health", False)
                 for g in device_groups if g.cfg == cfg)
        for cfg in by_cfg
    }
    # the predict reducer is a static flag too (ISSUE 16) AND sizes extra
    # state leaves: warm with the horizon the groups will dispatch, and
    # pass the flag EXPLICITLY — jit keys on how statics are passed, so a
    # defaulted kwarg here would compile a program the loop never reuses
    predict_by_cfg = {
        cfg: max((int(getattr(g, "predict", 0))
                  for g in device_groups if g.cfg == cfg), default=0)
        for cfg in by_cfg
    }
    for cfg, mls in by_cfg.items():
        G = next(g.G for g in device_groups if g.cfg == cfg)
        pk = predict_by_cfg[cfg]
        # one scratch state per config, threaded through every program
        # (chunk_step donates its state argument, so each call consumes
        # the previous call's output buffers — no HBM accumulation)
        scratch = replicate_state_device(
            init_state(cfg, seed, predict_horizon=pk), G)
        for m, lf in sorted(mls):
            vals = jnp.full((m, G, cfg.n_fields), jnp.nan, jnp.float32)
            ts = jnp.zeros((m, G), jnp.int32)
            scratch, _ = chunk_step(scratch, vals, ts, cfg, learn=lf,
                                    health=health_by_cfg[cfg],
                                    predict=bool(pk))
            counter.inc()
            warmed.add((m, cfg, lf))
        if include_claim:
            # the first-claim/realignment program (registry.claim_slot ->
            # set_state_row): the slot index is traced, so ONE execution
            # covers every future claim
            fresh = init_state(cfg, seed, predict_horizon=pk)
            scratch = set_state_row(
                scratch, {k: fresh[k] for k in scratch}, 0)
            counter.inc()
        del scratch
    return warmed
