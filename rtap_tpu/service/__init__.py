"""Service layer: stream-group registry, batched likelihood, alerting, loops.

The TPU-native analog of the reference's anomaly service (SURVEY.md L3,
§3.3): where the reference lazily creates one NuPIC model per node-metric
stream and loops over them in Python, this layer packs streams into
fixed-size groups that share one vmapped XLA program, keeps the
anomaly-likelihood post-process vectorized on host, and emits JSONL alerts.
"""

from rtap_tpu.service.alerts import AlertWriter, ThroughputCounter
from rtap_tpu.service.likelihood_batch import BatchAnomalyLikelihood
from rtap_tpu.service.registry import StreamGroup, StreamGroupRegistry

__all__ = [
    "AlertWriter",
    "BatchAnomalyLikelihood",
    "StreamGroup",
    "StreamGroupRegistry",
    "ThroughputCounter",
]
