"""Live metric sources for the service loop (SURVEY.md C18, L4).

The reference's metrics collector polls per-node stats endpoints at a fixed
cadence and normalizes them into (node, metric, t, value) tuples (SURVEY.md
§2.2 C18, §3.3). These adapters are that collector for the TPU service loop:
each is a callable matching `live_loop`'s source contract —
``source(tick) -> (values [G] f32, ts unix-sec)`` — batching one value per
registered stream id per tick, with NaN for streams the poll did not return
(the encoder's missing-sample path scores them without corrupting state).

Two transports:

- :class:`HttpPollSource` — pull. Polls one endpoint returning JSON
  ``{"ts": <unix>, "metrics": {"<stream_id>": <value>, ...}}`` (the
  Prometheus-exporter-style shape the reference scrapes).
- :class:`TcpJsonlSource` — push. A background listener accepts JSONL
  records ``{"id": ..., "value": ..., "ts": ...}`` from any number of
  producers; each tick drains the latest value per stream.
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
import time
import urllib.request

import numpy as np

from rtap_tpu.obs import get_registry

__all__ = ["HttpPollSource", "TcpJsonlSource", "BinaryBatchSource",
           "send_jsonl"]


def __getattr__(name):
    # The production wire-speed source lives in rtap_tpu.ingest
    # (ISSUE 7) but belongs to this module's source family — re-export
    # lazily so importing the JSONL sources never pays the ingest
    # package's import.
    if name == "BinaryBatchSource":
        from rtap_tpu.ingest.server import BinaryBatchSource

        return BinaryBatchSource
    raise AttributeError(name)


class HttpPollSource:
    """Poll an HTTP metrics endpoint once per tick.

    Stream ids absent from a poll (or a failed poll) yield NaN for that tick:
    a live service must keep scoring the healthy streams when one exporter
    times out, not stall the whole group (the reference's collector has the
    same per-poll timeout shape).

    Failed polls get bounded in-tick retry (`retry`, transport errors
    only) and a per-endpoint circuit breaker (`breaker`): after
    `fail_threshold` consecutive failed polls the endpoint is skipped
    outright — NaN tick, zero network wait — until the cooldown passes,
    then one half-open probe decides. Without the breaker a dead
    exporter's connect timeout would eat a fixed slice of EVERY tick's
    cadence budget for the whole outage. Short-circuited polls count in
    `polls_short_circuited` (and the breaker's own registry metrics), not
    in `poll_failures` — no network attempt was made.

    `track_unknown=True` (serve --auto-register over HTTP): metric KEYS in
    the poll payload that are not registered stream ids are remembered as
    discovery candidates — the reference's collector discovers a node's
    metrics from what the exporter reports, exactly this shape. Bounded
    like the TCP listener's capture (an exporter spraying keys must not
    grow host memory).
    """

    #: same bound as TcpJsonlSource.MAX_UNKNOWN_TRACKED
    MAX_UNKNOWN_TRACKED = 4096

    def __init__(self, url: str, stream_ids: list[str], timeout_s: float = 0.5,
                 track_unknown: bool = False, retry=None, breaker=None):
        from rtap_tpu.resilience.policies import CircuitBreaker, Retry

        self.url = url
        self.stream_ids = list(stream_ids)
        self._known = set(self.stream_ids)
        self.timeout_s = timeout_s
        self.poll_failures = 0
        self.polls_short_circuited = 0
        # retry covers transient transport blips inside one tick; delays
        # stay well under the 1 s cadence budget (2 tries, <= ~0.06 s of
        # backoff). Parse errors are NOT retried — a malformed payload is
        # the exporter's steady state, not a blip.
        self._retry = retry if retry is not None else Retry(
            attempts=2, base_delay_s=0.05, max_delay_s=0.25, op="http_poll")
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            fail_threshold=5, cooldown_s=30.0, name="http_poll")
        self._track_unknown = bool(track_unknown)
        self._unknown_seen: set[str] = set()
        self._obs_poll_failures = get_registry().counter(
            "rtap_obs_source_poll_failures_total",
            "HTTP metric polls that failed or timed out (whole-vector NaN "
            "ticks)")

    def _fetch(self) -> dict:
        with urllib.request.urlopen(self.url, timeout=self.timeout_s) as r:
            return json.loads(r.read().decode())

    def __call__(self, tick: int) -> tuple[np.ndarray, int]:
        values = np.full(len(self.stream_ids), np.nan, np.float32)
        ts = int(time.time())
        if not self._breaker.allow():
            # open breaker: the endpoint is known-dead; report missing
            # samples immediately instead of paying the connect timeout
            self.polls_short_circuited += 1
            return values, ts
        try:
            payload = self._retry.call(self._fetch, retry_on=(OSError,))
            metrics = payload.get("metrics", {})
            ts = int(payload.get("ts", ts))
            for i, sid in enumerate(self.stream_ids):
                v = metrics.get(sid)
                if v is None:
                    continue
                try:
                    values[i] = np.float32(v)
                except (TypeError, ValueError):  # rtap: allow[except-silent]
                    # one unconvertible metric (a version string, say) is
                    # THAT stream's missing sample, not a poll failure —
                    # the rest of the vector must still fill
                    pass
            if self._track_unknown and isinstance(metrics, dict):
                for key, v in metrics.items():
                    if not isinstance(key, str) or key in self._known:
                        continue
                    # discovery candidates must carry a usable numeric
                    # value: a string/null metric would claim a pad slot
                    # for a stream that can never score (and previously
                    # poison later polls)
                    try:
                        float(v)
                    except (TypeError, ValueError):
                        continue
                    if len(self._unknown_seen) < self.MAX_UNKNOWN_TRACKED:
                        self._unknown_seen.add(key)
            self._breaker.record_success()
        except Exception:
            self.poll_failures += 1
            self._obs_poll_failures.inc()
            self._breaker.record_failure()
        return values, ts

    # ---- dynamic membership (serve --auto-register) ----
    def drain_unknown(self) -> list[str]:
        """Pop unregistered metric keys seen in polls since the last drain
        (sorted for deterministic registration order)."""
        seen = sorted(self._unknown_seen)
        self._unknown_seen.clear()
        return seen

    def set_ids(self, stream_ids: list[str]) -> None:
        """Adopt the registry's (possibly grown/shrunk) dispatch order.
        Polling is stateless per tick — no value carry-over needed; the
        next poll simply fills the new vector by id."""
        self.stream_ids = list(stream_ids)
        self._known = set(self.stream_ids)


class TcpJsonlSource:
    """Push transport: listens on a TCP port for newline-delimited JSON
    records and keeps the latest value per stream; each tick snapshots them.

    Start/stop with a context manager (or .start()/.close()). The listener
    thread is a daemon; record parse errors are counted, never raised (a
    malformed producer must not kill the scoring loop).
    """

    #: bound on remembered unknown-id NAMES (track_unknown mode): a
    #: misbehaving producer spraying random ids must not grow host memory
    MAX_UNKNOWN_TRACKED = 4096

    def __init__(self, stream_ids: list[str], host: str = "127.0.0.1", port: int = 0,
                 native: bool | None = None, track_unknown: bool = False):
        self.stream_ids = list(stream_ids)
        self._index = {sid: i for i, sid in enumerate(self.stream_ids)}
        self._latest = np.full(len(self.stream_ids), np.nan, np.float32)
        self._latest_ts = 0
        self._lock = threading.Lock()
        self._py_parse_errors = 0
        self._py_unknown_ids = 0
        self._py_records = 0  # successes on the Python fallback path —
        # counted like the C parser's COUNTER_PARSED so records_parsed
        # (and rtap_obs_ingest_records_total) agree across parser
        # backends (ISSUE 7 satellite; pre-fix the Python path returned
        # None and the counter only moved natively)
        # track_unknown: remember the NAMES of unknown ids so serve
        # --auto-register can lazily create models for them (SURVEY.md
        # C19). Both parse paths capture names: the C parser appends them
        # to a bounded buffer drained each tick, the Python handler adds
        # them to the bounded set below.
        self._track_unknown = bool(track_unknown)
        self._unknown_seen: set[str] = set()
        # ingest health mirrored into the telemetry registry once per tick
        # (the delta sync in __call__): the parse tallies live in C/handler
        # state for per-record cheapness; _obs_synced remembers how much of
        # this instance's tally already landed in the global counters
        obs = get_registry()
        self._obs_synced = {"pe": 0, "uk": 0, "rec": 0}
        self._obs_parse_errors = obs.counter(
            "rtap_obs_ingest_parse_errors_total",
            "malformed JSONL records dropped by the TCP listener")
        self._obs_unknown_ids = obs.counter(
            "rtap_obs_ingest_unknown_ids_total",
            "records for unregistered stream ids (claim candidates under "
            "--auto-register, otherwise dropped)")
        self._obs_records = obs.counter(
            "rtap_obs_ingest_records_total",
            "successfully parsed ingest records (JSONL records and "
            "binary batch rows, both parser backends)")
        # Native C parse path (rtap_tpu/native/jsonl_parser.c): the whole
        # recv-chunk drain in one locked C call instead of per-record
        # json.loads + dict lookup + lock — the host core feeding 100k
        # streams cannot afford microseconds per record. native=None
        # auto-detects (falls back to Python if the toolchain/build is
        # unavailable); True requires it; False forces pure Python.
        self._nstate = None
        if native is not False:
            try:
                from rtap_tpu.native import NativeJsonlState

                self._nstate = NativeJsonlState(
                    self.stream_ids, self._latest,
                    track_unknown=self._track_unknown)
            except Exception:
                if native:
                    raise
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                if outer._nstate is not None:
                    conn = outer._nstate.new_conn()
                    try:
                        while True:
                            data = self.connection.recv(65536)
                            if not data:
                                break
                            with outer._lock:
                                conn.feed(data)
                        with outer._lock:
                            conn.flush()  # unterminated final line, like rfile
                    finally:
                        conn.close()
                    return
                for line in self.rfile:
                    try:
                        rec = json.loads(line)
                        sid = rec["id"]
                        # index resolved under the SAME lock as the write:
                        # set_ids swaps (_index, _latest) together, and an
                        # index from the old mapping must never address the
                        # new array (it would misroute the sample). Effect
                        # ORDER is pinned by the native-parity fuzz: the
                        # unknown check precedes value conversion (bad value
                        # on an unknown id = unknown, not parse error), and
                        # the value write precedes ts conversion (bad ts
                        # counts a parse error but KEEPS the value) — the C
                        # parser implements the same order.
                        with outer._lock:
                            i = outer._index.get(sid)
                            if i is None:
                                outer._py_unknown_ids += 1
                                if outer._track_unknown and \
                                        isinstance(sid, str) and \
                                        len(outer._unknown_seen) < \
                                        outer.MAX_UNKNOWN_TRACKED:
                                    outer._unknown_seen.add(sid)
                                continue
                            outer._latest[i] = np.float32(rec["value"])
                            outer._latest_ts = max(outer._latest_ts,
                                                   int(rec.get("ts", 0)))
                            # success is counted AFTER the ts conversion:
                            # a bad ts keeps the value but counts as a
                            # parse error, not a parsed record — the
                            # order the C parser implements (pinned by
                            # the native-parity fuzz)
                            outer._py_records += 1
                    except Exception:
                        # under the lock like every other tally: handler
                        # threads are one-per-connection, and an
                        # unguarded += across N malformed producers
                        # loses increments (read-modify-write race the
                        # analyzer's race pass flags)
                        with outer._lock:
                            outer._py_parse_errors += 1

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = self._server.server_address  # (host, bound port)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="rtap-sources-accept",
                                        daemon=True)

    def start(self) -> "TcpJsonlSource":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "TcpJsonlSource":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    @property
    def parse_errors(self) -> int:
        n = int(self._nstate.counters[1]) if self._nstate is not None else 0
        return self._py_parse_errors + n

    @property
    def unknown_ids(self) -> int:
        n = int(self._nstate.counters[2]) if self._nstate is not None else 0
        return self._py_unknown_ids + n

    @property
    def records_parsed(self) -> int:
        """Successful-record count — both parser backends (a record
        counts once its value AND ts converted, the C parser's rule)."""
        n = int(self._nstate.counters[0]) if self._nstate is not None else 0
        return self._py_records + n

    @property
    def native_active(self) -> bool:
        return self._nstate is not None

    # ---- dynamic membership (serve --auto-register) ----
    def drain_unknown(self) -> list[str]:
        """Pop the unknown-id names seen since the last drain (sorted for
        deterministic registration order). Empty unless track_unknown."""
        if not self._track_unknown:
            return []
        with self._lock:
            if self._nstate is not None:
                for sid in self._nstate.drain_unknown_names():
                    if len(self._unknown_seen) < self.MAX_UNKNOWN_TRACKED:
                        self._unknown_seen.add(sid)
            seen = sorted(self._unknown_seen)
            self._unknown_seen.clear()
        return seen

    def set_ids(self, stream_ids: list[str]) -> None:
        """Replace the accepted id set (registry membership changed).

        Latest values carry over BY ID — a retained stream must not lose
        the sample that arrived this tick — and new ids start at NaN. The
        snapshot order is the caller's (= the registry's dispatch order:
        live_loop routes values positionally). Works on both parse paths:
        the native table swaps under the same lock that serializes
        feed(), so per-connection parsers keep their partial-line state
        and observe the new table on their next line."""
        with self._lock:
            latest = np.full(len(stream_ids), np.nan, np.float32)
            for j, sid in enumerate(stream_ids):
                i = self._index.get(sid)
                if i is not None:
                    latest[j] = self._latest[i]
            if self._nstate is not None:
                self._nstate.set_table(stream_ids, latest)
            self.stream_ids = list(stream_ids)
            self._index = {sid: i for i, sid in enumerate(self.stream_ids)}
            self._latest = latest

    def __call__(self, tick: int) -> tuple[np.ndarray, int]:
        """Snapshot AND DRAIN: values reset to NaN after each tick, so a
        producer that stops pushing yields missing samples (NaN) rather than
        its stale last value being re-scored forever — a silent outage must
        surface as missing data, not as a suspiciously flat healthy metric."""
        with self._lock:
            values = self._latest.copy()
            self._latest[:] = np.nan
            if self._nstate is not None:
                self._latest_ts = max(self._latest_ts, int(self._nstate.ts_buf[0]))
            ts = self._latest_ts or int(time.time())
        # once-per-tick delta sync of THIS instance's ingest tallies into
        # the process-global registry counters (outside the lock: reads +
        # obs-cell increments only). Per-instance deltas, never a raise-
        # to-total sync against the global counter's current value: the
        # registry counter outlives any one source, so two sources over a
        # process lifetime (reconnect, tests) must SUM, and a replacement
        # source's from-zero tally must not be masked by its predecessor's.
        # Each tally is read ONCE into a local — the handler thread keeps
        # bumping it, and an inc/store pair reading twice would drop any
        # increments landing between the reads.
        pe = self.parse_errors
        self._obs_parse_errors.inc(max(0, pe - self._obs_synced["pe"]))
        self._obs_synced["pe"] = pe
        uk = self.unknown_ids
        self._obs_unknown_ids.inc(max(0, uk - self._obs_synced["uk"]))
        self._obs_synced["uk"] = uk
        n = self.records_parsed
        self._obs_records.inc(max(0, n - self._obs_synced["rec"]))
        self._obs_synced["rec"] = n
        return values, ts


#: records per sendall — bounds what one mid-stream connection drop can
#: leave in doubt (the failing batch is retried; earlier batches are known
#: delivered)
_SEND_BATCH = 512


def send_jsonl(address: tuple[str, int], records: list[dict],
               retry=None) -> int:
    """Producer-side helper (tests, demos, soak feeders): push records to
    a :class:`TcpJsonlSource` listener. Returns the count actually handed
    to the kernel.

    A listener restart mid-soak used to surface here as a raised
    ``ConnectionRefusedError`` that killed the producer; now the
    connection is retried with bounded exponential backoff (`retry`;
    default 4 attempts, <= ~1 s of total backoff) and the return value
    says how many records were delivered — the caller decides whether a
    shortfall is fatal. Delivery is at-least-once across retries: the
    batch in flight when a connection dropped is resent whole, which is
    harmless against TcpJsonlSource's latest-value-per-stream semantics.
    """
    from rtap_tpu.resilience.policies import Retry

    if retry is None:
        retry = Retry(attempts=4, base_delay_s=0.05, max_delay_s=0.5,
                      op="send_jsonl")
    payloads = [
        "".join(json.dumps(r) + "\n"
                for r in records[i:i + _SEND_BATCH]).encode()
        for i in range(0, len(records), _SEND_BATCH)
    ]
    sizes = [min(_SEND_BATCH, len(records) - i)
             for i in range(0, len(records), _SEND_BATCH)]
    delivered = 0
    next_batch = 0
    for attempt in range(1, retry.attempts + 1):
        try:
            with socket.create_connection(address, timeout=2.0) as s:
                while next_batch < len(payloads):
                    s.sendall(payloads[next_batch])
                    delivered += sizes[next_batch]
                    next_batch += 1
            return delivered
        except OSError:
            if attempt == retry.attempts:
                return delivered
            retry.backoff(attempt)
    return delivered
