"""Replay and live service loops — the reference's §3.3 tick loop, batched.

`replay_streams` drives a set of equal-length streams through stream groups
as fast as the chip allows (chunked scan dispatches); `live_loop` paces
ticks to a real cadence, polling a callable source each tick — the analog of
the reference's collector.poll() -> per-stream model.run() service loop.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.data.synthetic import LabeledStream
from rtap_tpu.service.alerts import AlertWriter, ThroughputCounter
from rtap_tpu.service.registry import StreamGroup, StreamGroupRegistry


@dataclass
class ReplayResult:
    stream_ids: list[str]
    timestamps: np.ndarray  # [T] int64 (shared clock)
    raw: np.ndarray  # [T, N] f32
    log_likelihood: np.ndarray  # [T, N] f64
    alerts: np.ndarray  # [T, N] bool
    predictions: np.ndarray | None = None  # [T, N] f32 when classifier enabled
    throughput: dict = field(default_factory=dict)


def replay_streams(
    streams: Sequence[LabeledStream],
    cfg: ModelConfig,
    backend: str = "tpu",
    group_size: int | None = None,
    chunk_ticks: int = 64,
    threshold: float = 0.5,
    alert_path: str | None = None,
    learn: bool = True,
) -> ReplayResult:
    """Replay equal-length streams through grouped models at full speed.

    All streams must share a clock (same length; timestamps of stream 0 are
    used for the result). Groups are sized `group_size` (default: all streams
    in one group) and each chunk of `chunk_ticks` ticks costs one device
    dispatch per group.
    """
    n = len(streams)
    T = len(streams[0].values)
    for s in streams:
        if len(s.values) != T:
            raise ValueError("replay_streams requires equal-length streams")
    group_size = group_size or n
    ids = [s.stream_id for s in streams]

    reg = StreamGroupRegistry(cfg, group_size=group_size, backend=backend, threshold=threshold)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()

    values = np.stack([s.values for s in streams], axis=1)  # [T, N]
    ts = np.stack([s.timestamps for s in streams], axis=1).astype(np.int64)  # [T, N]

    raw = np.empty((T, n), np.float32)
    loglik = np.empty((T, n), np.float64)
    alerts = np.zeros((T, n), bool)
    preds = np.empty((T, n), np.float32) if cfg.classifier.enabled else None
    writer = AlertWriter(alert_path)
    counter = ThroughputCounter()

    # streams were added in order, so group i owns the contiguous slice
    # ids[i*group_size : i*group_size + n_live], at slots 0..n_live-1
    for gi, grp in enumerate(reg.groups):
        lo = gi * group_size
        live = grp.n_live
        sids = ids[lo : lo + live]
        # pad slots replay the first live stream's data; their scores are dropped
        gv = np.repeat(values[:, lo : lo + 1], grp.G, axis=1)
        gt = np.repeat(ts[:, lo : lo + 1], grp.G, axis=1)
        gv[:, :live] = values[:, lo : lo + live]
        gt[:, :live] = ts[:, lo : lo + live]

        def collect(span, handle):
            t0, t1 = span
            r, ll, al = grp.collect_chunk(handle)
            raw[t0:t1, lo : lo + live] = r[:, :live]
            loglik[t0:t1, lo : lo + live] = ll[:, :live]
            alerts[t0:t1, lo : lo + live] = al[:, :live]
            if preds is not None:
                preds[t0:t1, lo : lo + live] = grp.last_predictions[:, :live]
            counter.add((t1 - t0) * live)
            for i in range(t0, t1):
                writer.emit_batch(sids, gt[i, :live], gv[i, :live],
                                  r[i - t0, :live], ll[i - t0, :live], al[i - t0, :live])

        # depth-2 pipeline: the device computes chunk t+1 while the host
        # post-processes chunk t (SURVEY.md §7 hard part 3 — overlapped feed)
        pending: deque = deque()
        for t0 in range(0, T, chunk_ticks):
            t1 = min(t0 + chunk_ticks, T)
            pending.append(((t0, t1), grp.dispatch_chunk(gv[t0:t1], gt[t0:t1], learn=learn)))
            if len(pending) >= 2:
                collect(*pending.popleft())
        while pending:
            collect(*pending.popleft())
    writer.close()

    return ReplayResult(
        stream_ids=ids,
        timestamps=streams[0].timestamps,
        raw=raw,
        log_likelihood=loglik,
        alerts=alerts,
        predictions=preds,
        throughput={**counter.stats(), "alerts": writer.count, **_occupancy()},
    )


def live_loop(
    source: Callable[[int], tuple[np.ndarray, int]],
    group: StreamGroup,
    n_ticks: int,
    cadence_s: float = 1.0,
    alert_path: str | None = None,
) -> dict:
    """Paced live scoring: each tick, poll `source(tick) -> (values [G], ts)`,
    score the group, emit alerts; sleep off any time left in the cadence
    budget. Returns throughput stats including missed-deadline count — the
    real-time health signal for the 1s-cadence north star."""
    writer = AlertWriter(alert_path)
    counter = ThroughputCounter()
    missed = 0
    latencies = np.empty(n_ticks, np.float64)  # per-tick poll->emit seconds
    live = getattr(group, "n_live", group.G)  # never emit for registry pad slots
    for k in range(n_ticks):
        t_start = time.perf_counter()
        values, ts = source(k)
        res = group.tick(values, ts)
        writer.emit_batch(group.stream_ids[:live], np.full(live, ts), values[:live],
                          res.raw[:live], res.log_likelihood[:live], res.alerts[:live])
        counter.add(live)
        elapsed = time.perf_counter() - t_start
        latencies[k] = elapsed
        budget = cadence_s - elapsed
        if budget < 0:
            missed += 1
        elif k + 1 < n_ticks:
            time.sleep(budget)
    writer.close()
    lat = {}
    if n_ticks > 0:
        lat = {
            f"latency_p{p}_ms": round(float(np.percentile(latencies, p)) * 1e3, 3)
            for p in (50, 90, 99)
        }
        lat["latency_max_ms"] = round(float(latencies.max()) * 1e3, 3)
    return {**counter.stats(), "alerts": writer.count, "missed_deadlines": missed,
            "ticks": n_ticks, "cadence_s": cadence_s, **lat, **_occupancy()}


def _occupancy() -> dict:
    """Device HBM occupancy for the throughput stats (observability —
    SURVEY.md §5 metrics/logging). Empty when the backend exposes none
    (CPU test backend). Only consulted when jax is ALREADY in use: a pure
    CPU-oracle run must not initialize the TPU backend as a stats side
    effect (backend init can hang on a wedged tunnel, and would claim the
    exclusive chip out from under a concurrent device run)."""
    import sys

    if "jax" not in sys.modules:
        return {}
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        out = {}
        if "bytes_in_use" in stats:
            out["hbm_bytes_in_use"] = int(stats["bytes_in_use"])
        if "peak_bytes_in_use" in stats:
            out["hbm_peak_bytes_in_use"] = int(stats["peak_bytes_in_use"])
        return out
    except Exception:
        return {}
