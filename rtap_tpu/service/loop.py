"""Replay and live service loops — the reference's §3.3 tick loop, batched.

`replay_streams` drives a set of equal-length streams through stream groups
as fast as the chip allows (chunked scan dispatches); `live_loop` paces
ticks to a real cadence, polling a callable source each tick — the analog of
the reference's collector.poll() -> per-stream model.run() service loop.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Sequence

import numpy as np

from rtap_tpu.config import ModelConfig
from rtap_tpu.data.synthetic import LabeledStream
from rtap_tpu.obs import TickWatchdog, get_registry
from rtap_tpu.service.alerts import AlertWriter, ThroughputCounter
from rtap_tpu.service.registry import (
    PAD_PREFIX,
    StreamGroup,
    StreamGroupRegistry,
    _Slot as _RegistrySlot,
)

#: bound on remembered rejected-id names under --auto-register (mirrors
#: TcpJsonlSource.MAX_UNKNOWN_TRACKED: an id-spraying producer must not
#: grow a long-lived server's memory); the REJECTED COUNT keeps counting
_MAX_REJECTED_TRACKED = 4096

#: the tick phases the loop accounts wall seconds to; one
#: rtap_obs_phase_seconds histogram per phase (docs/TELEMETRY.md)
_PHASES = ("source", "membership", "dispatch", "collect", "emit", "checkpoint")


def _alert_gid(gi: int, grp):
    """The alert_id group field: the bare group index on a group's
    original timeline, `<gi>.e<epoch>` after a quarantine restore has
    rewound its tick counter (docs/TELEMETRY.md alert schema)."""
    epoch = getattr(grp, "alert_epoch", 0)
    return gi if not epoch else f"{gi}.e{epoch}"


def _scored_counter():
    return get_registry().counter(
        "rtap_obs_scored_total",
        "anomaly-scored (stream, tick) samples emitted — the north-star "
        "metrics counter (live + replay)")


def _sync_source_membership(source, reg) -> None:
    """Push the registry's membership to the source after any change.

    Slot-map-addressed sources (rtap_tpu.ingest.BinaryBatchSource) get
    the (shard, group, slot) map — the registry hands out ADDRESSES,
    not a flat id list (ROADMAP-1); flat-id sources (TcpJsonlSource,
    HttpPollSource) keep their dispatch-order id list. Sources without
    either contract re-derive per tick (the length check is the guard).
    """
    if hasattr(source, "set_slot_map"):
        source.set_slot_map(reg.slot_map())
    elif hasattr(source, "set_ids"):
        source.set_ids(reg.dispatch_ids())


@dataclass
class ReplayResult:
    stream_ids: list[str]
    timestamps: np.ndarray  # [T] int64 (shared clock)
    raw: np.ndarray  # [T, N] f32
    log_likelihood: np.ndarray  # [T, N] f64
    alerts: np.ndarray  # [T, N] bool
    predictions: np.ndarray | None = None  # [T, N] f32 when classifier enabled
    throughput: dict = field(default_factory=dict)


def replay_streams(
    streams: Sequence[LabeledStream],
    cfg: ModelConfig,
    backend: str = "tpu",
    group_size: int | None = None,
    chunk_ticks: int = 64,
    threshold: float = 0.5,
    alert_path: str | None = None,
    learn: bool = True,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    debounce: int = 1,
    trace=None,
) -> ReplayResult:
    """Replay equal-length streams through grouped models at full speed.

    All streams must share a clock (same length; timestamps of stream 0 are
    used for the result). Groups are sized `group_size` (default: all streams
    in one group) and each chunk of `chunk_ticks` ticks costs one device
    dispatch per group.

    Crash recovery (SURVEY.md §5 checkpoint/resume as *elastic recovery*):
    with `checkpoint_dir` + `checkpoint_every=k`, each group's full resume
    state (model + likelihood ring + tick count) is saved atomically every k
    collected chunks — the depth-2 pipeline is DRAINED first, because a
    donated in-flight chunk means the device state is already ahead of the
    last collected tick. On a later call with the same `checkpoint_dir`, any
    group with a checkpoint resumes from its recorded tick instead of from
    scratch; ticks before the resume point are left NaN in the result (they
    were scored by the earlier, killed run) and `throughput["resumed_from"]`
    records the boundary. tests/integration/test_crash_resume.py kills a
    replay mid-stream and proves score-identical continuation.
    """
    n = len(streams)
    T = len(streams[0].values)
    for s in streams:
        if len(s.values) != T:
            raise ValueError("replay_streams requires equal-length streams")
    group_size = group_size or n
    ids = [s.stream_id for s in streams]

    reg = StreamGroupRegistry(cfg, group_size=group_size, backend=backend,
                              threshold=threshold, debounce=debounce)
    for sid in ids:
        reg.add_stream(sid)
    reg.finalize()

    values = np.stack([s.values for s in streams], axis=1)  # [T, N]
    ts = np.stack([s.timestamps for s in streams], axis=1).astype(np.int64)  # [T, N]

    raw = np.full((T, n), np.nan, np.float32)
    loglik = np.full((T, n), np.nan, np.float64)
    alerts = np.zeros((T, n), bool)
    # NaN-fill like raw/loglik: on a resumed run the pre-resume rows were
    # scored by the earlier (killed) process and must read as absent here
    preds = np.full((T, n), np.nan, np.float32) if cfg.classifier.enabled else None
    writer = AlertWriter(alert_path)
    counter = ThroughputCounter()
    obs_scored = _scored_counter()
    obs_replay_ticks = get_registry().counter(
        "rtap_obs_replay_group_ticks_total",
        "group-ticks collected by replay_streams (sums over groups)")
    resumed_from: dict[str, int] = {}
    suppression_scanned_from: int | None = None  # lowest alert-cursor
    # offset whose tail has been scanned into the suppression set

    # streams were added in order, so group i owns the contiguous slice
    # ids[i*group_size : i*group_size + n_live], at slots 0..n_live-1
    groups_with_work = 0  # groups that will replay at least one tick
    for gi, grp in enumerate(reg.groups):
        ck_path = None
        if checkpoint_dir is not None:
            import os

            from rtap_tpu.service.shardpath import group_checkpoint_path

            ck_path = group_checkpoint_path(checkpoint_dir, gi)
            if os.path.isdir(ck_path):
                from rtap_tpu.service.checkpoint import load_group, validate_resume

                resumed = load_group(ck_path)
                # shared resume-safety gate (stream ids + config + alerting
                # semantics) — one implementation for replay and live serve
                validate_resume(resumed, ck_path, grp)
                if resumed.ticks % chunk_ticks and resumed.ticks < T:
                    raise ValueError(
                        f"checkpoint {ck_path} at tick {resumed.ticks} is not "
                        f"on the chunk grid ({chunk_ticks}); replay it with "
                        "the chunk size it was saved under"
                    )
                grp = reg.groups[gi] = resumed
                resumed_from[f"group{gi}"] = grp.ticks
                ck_off = getattr(grp, "resume_alerts_offset", None)
                if alert_path is not None and ck_off is not None and (
                        suppression_scanned_from is None
                        or ck_off < suppression_scanned_from):
                    # exactly-once across the crash: alert ids the dead
                    # run already delivered past the checkpoints' alert
                    # cursors are suppressed, not duplicated, when the
                    # tail is re-scored. ONE tail scan covers every
                    # group (ids are globally unique); only a torn save
                    # set revealing an even older cursor rescans.
                    from rtap_tpu.service.alerts import scan_alert_ids

                    writer.arm_suppression(
                        scan_alert_ids(alert_path, ck_off))
                    suppression_scanned_from = ck_off
        if grp.ticks < T:
            groups_with_work += 1
        # a group resumed AT the end replays zero ticks (all-NaN rows) by
        # design: its scores belong to the earlier run. That is only valid
        # while some OTHER group still has work — the all-complete case is
        # guarded after this loop.
        lo = gi * group_size
        live = grp.n_live
        sids = ids[lo : lo + live]
        # pad slots replay the first live stream's data; their scores are dropped
        gv = np.repeat(values[:, lo : lo + 1], grp.G, axis=1)
        gt = np.repeat(ts[:, lo : lo + 1], grp.G, axis=1)
        gv[:, :live] = values[:, lo : lo + live]
        gt[:, :live] = ts[:, lo : lo + live]

        def collect(span, handle):
            t0, t1 = span
            tc0 = time.perf_counter() if trace is not None else 0.0
            r, ll, al = grp.collect_chunk(handle)
            if trace is not None:
                # chunk-granularity spans (replay has no cadence): the
                # correlation tick is the chunk's first tick
                trace.add_span("replay_collect", t0, tc0,
                               time.perf_counter() - tc0, group=gi)
            raw[t0:t1, lo : lo + live] = r[:, :live]
            loglik[t0:t1, lo : lo + live] = ll[:, :live]
            alerts[t0:t1, lo : lo + live] = al[:, :live]
            if preds is not None:
                preds[t0:t1, lo : lo + live] = grp.last_predictions[:, :live]
            counter.add((t1 - t0) * live)
            obs_scored.inc((t1 - t0) * live)
            obs_replay_ticks.inc(t1 - t0)
            for i in range(t0, t1):
                # alert_id group:stream:tick — the replay tick IS the
                # group's tick counter (both started at 0 together);
                # epoch-suffixed if the resumed checkpoint carries a
                # rewound-timeline epoch from a live quarantine restore
                writer.emit_batch(sids, gt[i, :live], gv[i, :live],
                                  r[i - t0, :live], ll[i - t0, :live],
                                  al[i - t0, :live],
                                  group=_alert_gid(gi, grp), tick=i)

        # depth-2 pipeline: the device computes chunk t+1 while the host
        # post-processes chunk t (SURVEY.md §7 hard part 3 — overlapped feed)
        pending: deque = deque()
        chunks_done = 0
        for t0 in range(grp.ticks, T, chunk_ticks):
            t1 = min(t0 + chunk_ticks, T)
            td0 = time.perf_counter() if trace is not None else 0.0
            handle = grp.dispatch_chunk(gv[t0:t1], gt[t0:t1], learn=learn)
            if trace is not None:
                trace.add_span("replay_dispatch", t0, td0,
                               time.perf_counter() - td0, group=gi)
            pending.append(((t0, t1), handle))
            if len(pending) >= 2:
                collect(*pending.popleft())
                chunks_done += 1
            if learn and ck_path is not None and checkpoint_every and \
                    chunks_done and chunks_done % checkpoint_every == 0 \
                    and pending:
                # drain before saving: grp.state must correspond exactly to
                # the last COLLECTED tick or resume would double-step
                while pending:
                    collect(*pending.popleft())
                    chunks_done += 1
                from rtap_tpu.service.checkpoint import save_group

                # drained instant: flush the sink so the alert cursor in
                # meta equals the on-disk size (exactly-once resume)
                writer.flush_sink()
                save_group(grp, ck_path, alerts_offset=writer.sink_offset())
        while pending:
            collect(*pending.popleft())
            chunks_done += 1
        if learn and ck_path is not None and checkpoint_every and grp.ticks >= T:
            from rtap_tpu.service.checkpoint import save_group

            writer.flush_sink()
            # final state, resumable past the end
            save_group(grp, ck_path, alerts_offset=writer.sink_offset())
            # (frozen replay never writes — read-only like serve --freeze)
    writer.close()
    if resumed_from and not groups_with_work:
        # every group's checkpoint is already at tick >= T: the whole replay
        # silently scored ZERO ticks and would return all-NaN (frozen or
        # learning alike). Resume exists to continue interrupted runs;
        # re-scoring a corpus through a trained model is serve --freeze.
        raise ValueError(
            f"checkpoint dir {checkpoint_dir} resumes every group at tick >= "
            f"replay length {T}: nothing left to replay. To re-score this "
            "corpus through the trained model, serve it with --freeze; to "
            "keep learning, replay a longer stream or a fresh checkpoint dir."
        )

    stats = {**counter.stats(), "alerts": writer.count, **_occupancy()}
    overflow = _overflow_total(reg.groups)
    if overflow is not None:
        # kernel capacity-overflow observability (learn_cap/col_cap/
        # punish_cap/fanout_cap): nonzero means some stream exceeded a
        # static bound and its scores deviate from the oracle — surface it
        # in the replay stats instead of leaving it buried in device state
        stats["tm_overflow_total"] = overflow
    if resumed_from:
        stats["resumed_from"] = resumed_from
    return ReplayResult(
        stream_ids=ids,
        timestamps=streams[0].timestamps,
        raw=raw,
        log_likelihood=loglik,
        alerts=alerts,
        predictions=preds,
        throughput=stats,
    )


def live_loop(
    source: Callable[[int], tuple[np.ndarray, int]],
    group: StreamGroup | StreamGroupRegistry,
    n_ticks: int,
    cadence_s: float = 1.0,
    alert_path: str | None = None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    stop_event=None,
    pipeline_depth: int = 1,
    dispatch_threads: int = 1,
    learn: bool = True,
    auto_register: bool = False,
    auto_release_after: int = 0,
    micro_chunk: int = 1,
    chunk_stagger: bool = False,
    chaos=None,
    degradation=None,
    quarantine_restore_after: int = 0,
    alert_flush_every: int = 1,
    aot_warmup: bool = False,
    trace=None,
    flight=None,
    attributor=None,
    journal=None,
    health=None,
    lease=None,
    resume_suppression=None,
    correlator=None,
    latency=None,
    slo=None,
    predictor=None,
    fleet=None,
) -> dict:
    """Paced live scoring: each tick, poll `source(tick) -> (values [G], ts)`,
    score the group(s), emit alerts; sleep off any time left in the cadence
    budget. Returns throughput stats including missed-deadline count — the
    real-time health signal for the 1s-cadence north star.

    `auto_register=True` (with a registry + a source exposing
    `drain_unknown`/`set_ids`, i.e. TcpJsonlSource(track_unknown=True)):
    unknown stream ids arriving on the wire lazily claim free pad slots —
    the reference's model-per-new-metric creation (SURVEY.md C19) without
    recompiling (shapes are static; a claimed slot's model state,
    likelihood probation, and debounce reset — registry.add_stream).
    Capacity = pad slots (group-size rounding + `finalize(reserve=)` +
    released streams); ids beyond capacity are counted in
    `auto_rejected` and not retried.

    `auto_release_after=N` (registry only) is the elastic shrink: a
    stream silent (all-NaN) for N consecutive ticks releases its slot
    back to claimable capacity — a churning monitored cluster (nodes
    leaving) must not exhaust slots. Releases defer to the next tick's
    membership block under the same drain-first rule as claims; a
    released stream that pushes again re-registers as a NEW model (with
    auto_register — a release also clears the rejected-id memory so
    leave-then-join churn converges). N must comfortably exceed ordinary
    outage lengths: the NaN missing-sample semantics deliberately keep
    scoring through gaps, and release discards the model's learned
    context. Source contract under shrink: TcpJsonlSource adapts via
    `set_ids`; a custom callable must size its vector to the registry's
    CURRENT `dispatch_ids()` each tick (a fixed-length callable fails
    the length check loudly on the tick after a release).

    `learn=False` freezes the models (NuPIC `disableLearning()` parity —
    SURVEY §3.2 OPF model surface): SP/TM/classifier state is
    bit-identical after any number of frozen ticks, while raw scores and
    alerts still flow and the anomaly LIKELIHOOD keeps adapting (it is
    the score normalizer, downstream of the model, exactly as in the
    reference's likelihood-outside-the-model layering). Frozen inference
    skips the learning pass, which the silicon ablations put at ~85% of
    the fused step (~155k metrics/s/chip inference-only — SCALING.md).

    `pipeline_depth=2` overlaps the device round trip with the cadence
    sleep: tick k's results are collected and emitted after tick k+1 is
    dispatched, hiding the per-group dispatch+collect latency that
    dominates single-tick dispatches on a remote chip (the tunnel RTT made
    the 16x256 production soak miss every 1 s deadline at depth 1 —
    reports/live_soak.json). Alerts lag one cadence; checkpoint saves
    drain the pipeline first, so nothing is in flight at save time.

    `dispatch_threads=N` issues the per-group dispatch and collect calls
    from a thread pool instead of serially. Depth 2 alone did NOT fix the
    16x256 shape over the remote-chip tunnel (p50 stayed 1.07 s —
    reports/live_soak_pipelined.json): on that link each dispatch_chunk
    is itself a blocking ~65 ms RPC (transfer + launch), so 16 groups
    serialize ~1.04 s of round trips per tick no matter when collection
    happens. Local backends enqueue asynchronously and don't need this.
    Threading overlaps the RPCs; groups are independent objects (each
    thread touches exactly one group's state and likelihood ring) and
    emission stays serial in group order after all collects join, so
    output is bit-identical to the serial schedule
    (tests/unit/test_multigroup_serve.py pins it).

    `micro_chunk=M` batches M consecutive ticks into ONE device dispatch
    per group (the chunked scan path, T=M). The 100k-soak forensics
    (reports/live_soak_100k_t48.json and SCALING.md round 5) measured a
    ~12 ms device-side invocation floor PER PROGRAM on the tunnel-attached
    runtime — at 100 groups that alone is 1.2 s/tick, unfixable by
    threads (48 threads moved nothing) or cadence (k=4 moved nothing).
    Micro-chunking divides the program count by M; the price is alert
    latency: a record is scored up to (M-1) ticks after arrival, plus the
    usual (pipeline_depth-1) chunks of collect lag — total staleness
    <= (pipeline_depth*M - 1) ticks. Deadlines stay per-tick: boundary
    ticks carry the whole chunk's dispatch+collect inside one cadence
    budget. Membership changes, routing rebuilds, and periodic
    checkpoints FORCE a chunk boundary (partial buffers flush, the
    pipeline drains, staggered boundaries re-ramp): claims/releases and
    saves compose with any chunking at the cost of one spiky tick per
    batch — right for churn at tens-of-seconds cadence, wrong for
    per-tick churn (drop micro_chunk there).

    Accepts a single :class:`StreamGroup` or a finalized
    :class:`StreamGroupRegistry`. Measured chip throughput PEAKS at small
    group sizes (SCALING.md bench G-sweep: nothing amortizes with G), so
    at-scale serving is many groups per chip, not one giant group: with a
    registry, each tick dispatches EVERY group before collecting ANY
    (dispatch_chunk/collect_chunk), so the device queue holds all groups'
    step programs back to back while the host does per-group likelihood —
    the interleaved schedule of scripts/multigroup_sched.py as the
    production serve path. `source` values align with the registry's
    stream registration order (contiguous per-group slices).

    Fault containment (docs/RESILIENCE.md): a dispatch or collect
    exception QUARANTINES that group — it stops being scored, a
    structured ``group_quarantined`` event lands on the alert stream, and
    every other group keeps its cadence (groups are independent; one
    group's wedged device program must not take down the fleet). With
    `checkpoint_dir` and `quarantine_restore_after=N`, a quarantined
    group is re-loaded from its last checkpoint N ticks later
    (``group_restored``); a failed restore gives up loudly
    (``group_restore_failed``) and the group stays quarantined. A source
    that RAISES (vs. returning NaN) is caught: the tick scores a
    whole-vector missing sample and counts ``rtap_obs_source_errors_total``;
    timestamps going backwards are clamped monotonic and counted.
    Checkpoint save failures are per-group events (the atomic save left
    the previous checkpoint intact); 3 consecutive failed rounds open a
    breaker that quarantines checkpointing until its cooldown. The alert
    sink is non-fatal end to end (AlertWriter retry-then-quarantine).

    `degradation` (a resilience.DegradationController) sheds load under
    sustained deadline misses down the declared ladder: learn_thin →
    score_only → tick_widen, with hysteresis, ``degraded``/``recovered``
    events and the ``rtap_obs_degradation_level`` gauge. The controller
    only ever REMOVES learning or widens the effective cadence — scores
    and alerts keep flowing at every level.

    `chaos` (a resilience.ChaosEngine) injects scripted faults at the
    loop's seams — source, per-group dispatch/collect, alert sink file,
    checkpoint saves — for deterministic recovery-path testing
    (scripts/chaos_soak.py, serve --chaos-spec). None = no injection and
    zero hot-path cost.

    `trace` (an obs.TraceRecorder) records the per-tick timeline: every
    phase interval the loop already clocks becomes a span (plus a
    whole-tick span and per-group dispatch/collect child spans from
    inside the fault-capture wrappers), and every watchdog/resilience
    event becomes an instant at the same tick — exported as
    Perfetto-loadable Chrome trace JSON (serve --trace-out, GET /trace).
    The membership and checkpoint spans are positioned at their block
    start with the BOOKED duration (the same drain-exclusion arithmetic
    the phase histograms use), so their on-screen width matches the
    attributed cost, not the raw wall interval. None = zero hot-path
    cost.

    `flight` (an obs.FlightRecorder) keeps a bounded black-box ring of
    the last N ticks (latency, per-phase deltas, per-group scored
    digest, deadline verdicts, recent events) and auto-dumps an atomic
    postmortem bundle on group quarantine, degradation-level change, or
    a missed-tick burst (docs/POSTMORTEM.md). Dumps are queued mid-tick
    and written AFTER the tick's deadline accounting, so the bundle
    write itself shows up (honestly) in the NEXT tick's budget, never
    inside a phase span.

    `attributor` (a service.attribution.AlertAttributor) adds per-alert
    `top_fields` provenance to alert JSONL lines (serve
    --alert-attribution): the fields whose encoder representation moved
    most, decoded in RDSE key-space (docs/TELEMETRY.md).

    `journal` (a resilience.TickJournal, serve --journal-dir; ISSUE 5
    durability): every ingested tick row is appended to the write-ahead
    journal BEFORE scoring, and on entry any recovered rows past each
    group's checkpoint tick are REPLAYED through the normal scoring
    path — the resumed fleet reaches the crash point bit-identically to
    an uninterrupted run, with already-delivered alert ids suppressed
    via the checkpoint's alert cursor (exactly-once across the crash).
    After each emitted chunk the journal records an alert-delivery
    cursor; after each successful checkpoint round it is compacted to
    the ticks the checkpoints no longer cover. A torn/corrupt journal
    tail was already truncated (counted) when the caller constructed
    the TickJournal — recovery never refuses to start
    (docs/RESILIENCE.md durability section; scripts/crash_soak.py is
    the kill-9 acceptance soak).

    `lease` (a resilience.replicate.Lease, ISSUE 8 hot-standby
    failover): the leadership lease this loop serves under. Freshness
    rides the lease's heartbeat thread (started here if the caller has
    not already); the loop probes ``still_mine()`` at the top of every
    tick, and a probe that finds the lease's fencing epoch advanced
    past ours (a standby promoted while this process was
    paused/partitioned) FENCES the loop — a ``leader_fenced`` event, an
    orderly break (``stats["fenced"] = True``; serve exits
    ``replicate.FENCED_RC``), and the AlertWriter's own fence guard
    refuses any stragglers, so a zombie old leader can never append to
    the alert sink the new leader now owns (docs/RESILIENCE.md failover
    runbook). None = no lease discipline (the single-process default).

    `health` (an obs.HealthTracker, serve --health; ISSUE 6): when the
    groups were built with ``health=True``, every collected chunk
    carries the fused on-device model-health leaf
    (ops/health_tpu.py — segment-pool occupancy, permanence sketch,
    SDR sparsity, predicted->active hit rate, score histogram; pure
    reads, bit-exact-neutral) and the tracker folds it into per-group
    scorecards with EWMA score-drift detection. Health incidents
    (``pool_saturated`` / ``sparsity_collapsed`` / ``score_drift``)
    ride the alert/incident stream like watchdog events and request a
    flight-recorder postmortem dump like a quarantine does. The
    scorecards serve at ``GET /health`` and land in
    ``stats["health"]``. None = leaves (if any) are simply not folded.

    `correlator` (a correlate.IncidentCorrelator, serve --topology;
    ISSUE 9): every alert the writer emits folds into topology-cluster
    correlation windows, and quiesced windows close into cluster-level
    ``incident`` events on the same stream (member alert_ids, blast-
    radius node set, onset tick, attributed fields) — blast-radius
    detection over the per-stream alert stream. The fold keys on the
    stable PR 5 alert_ids and the SOURCE clock, and on resume the
    correlator re-folds the sink tail through the shared tolerant line
    walker, so the incident stream is exactly-once across kill-9/
    journal-replay/failover by construction (scripts/workload_soak.py
    is the acceptance soak; docs/WORKLOADS.md the runbook). None = no
    correlation and zero hot-path cost.

    `latency` (an obs.LatencyTracker, serve --latency; ISSUE 11): the
    detection-latency observability layer. Each tick folds the stage
    waterfall (source ts -> poll -> dispatch -> collect -> emit) into
    bounded windowed quantile sketches and polls the wired lag
    providers (replication-ack lag, incident-close lag); the
    AlertWriter feeds the per-alert end-to-end ``detect`` sketch at
    sink-write time. Pure observation — host wall clocks and
    timestamps already riding the rows, zero extra device↔host
    fetches, and the alert stream + model state are byte/bit-identical
    with the tracker on or off (tests/integration/
    test_latency_serve.py pins it). None = zero hot-path cost.

    `slo` (an obs.SloTracker, serve --slo NAME=TARGET@pQ): operator-
    declared latency SLOs evaluated per tick with fast/slow multi-
    window burn rates; edge-triggered ``slo_burn``/``slo_recovered``/
    ``slo_budget_exhausted`` events ride the alert stream like
    watchdog events, a fast burn requests a flight-recorder postmortem
    dump, and the run's verdict lands in ``stats["slo"]``
    (docs/SLO.md). Requires `latency` (it is the measurement source).

    `predictor` (a predict.PredictTracker, serve --predict; ISSUE 16):
    when the groups were built with ``predict=k``, every collected
    chunk carries the fused on-device predictive-horizon leaf
    (ops/predict_tpu.py — horizon-old predicted-column overlap vs the
    tick's actual input, per-stream divergence EWMA, predicted
    sparsity; pure reads, bit-exact-neutral) and the tracker folds it
    into per-stream divergence trajectories. Edge-triggered
    ``precursor`` events (stable alert ids, predicted lead time) ride
    the alert stream and request flight-recorder dumps; with an
    attached BlastFuser (serve --predict + --topology) the first
    precursor in a topology cluster pages ONE ``predicted_incident``
    with the predicted blast radius. On resume the event ids already
    on disk are re-armed for suppression (service/alerts.
    scan_event_ids) so a journal replay never pages twice. Scorecards
    serve at ``GET /predict`` and land in ``stats["predict"]``. None =
    leaves (if any) are simply not folded.

    Service restarts (SURVEY.md §5 checkpoint/resume, C16): with
    `checkpoint_dir` + `checkpoint_every=k`, every group's full resume
    state is saved atomically every k ticks (the in-flight pipeline is
    drained before each save, so nothing is in flight), and a later call with
    the same dir resumes each group from its recorded tick — same
    validation as replay_streams (stream ids, config, alerting semantics
    must match the checkpoint; mismatches are errors, not surprises).
    Saves run inline, so a checkpoint tick may miss its cadence deadline —
    pick `checkpoint_every` with that cost in mind (it is visible in
    `latency_max_ms` and the missed-deadline count). Checkpointing
    requires a registry (the resumed instances replace `group.groups[i]`,
    which a bare StreamGroup argument could not observe).
    """
    if pipeline_depth < 1:
        raise ValueError(f"pipeline_depth must be >= 1; got {pipeline_depth}")
    if micro_chunk < 1:
        raise ValueError(f"micro_chunk must be >= 1; got {micro_chunk}")
    if chunk_stagger and micro_chunk < 2:
        raise ValueError("chunk_stagger needs micro_chunk >= 2")
    if dispatch_threads < 1:
        raise ValueError(f"dispatch_threads must be >= 1; got {dispatch_threads}")
    if quarantine_restore_after < 0:
        raise ValueError(
            f"quarantine_restore_after must be >= 0; got "
            f"{quarantine_restore_after}")
    if quarantine_restore_after and checkpoint_dir is None:
        raise ValueError(
            "quarantine_restore_after needs --checkpoint-dir: restore means "
            "re-loading the group's last checkpoint")
    if isinstance(group, StreamGroupRegistry):
        # _pending empty is NOT finalized: a stream count that is an exact
        # multiple of group_size seals its last group with nothing pending,
        # yet post-finalize membership (claims, releases, version bumps)
        # still requires finalize() — an elastic loop on an unfinalized
        # registry would buffer claims into _pending, invisible to this
        # loop's groups snapshot
        if group._pending or not group._finalized:
            raise ValueError(
                "live_loop needs a finalized registry (finalize() seals the "
                f"last group; {len(group._pending)} streams pending, "
                f"finalized={group._finalized})")
        groups = group.groups  # the live list: resume replaces entries in place
    else:
        if checkpoint_dir is not None:
            raise ValueError(
                "live_loop checkpointing needs a StreamGroupRegistry (a bare "
                "StreamGroup caller could not observe the resumed instances)")
        groups = [group]
    resumed_from: dict[str, int] = {}
    if checkpoint_dir is not None:
        import os

        from rtap_tpu.service.checkpoint import load_group, validate_resume
        from rtap_tpu.service.shardpath import group_checkpoint_path

        for gi, grp in enumerate(groups):
            ck_path = group_checkpoint_path(checkpoint_dir, gi)
            if not os.path.isdir(ck_path):
                continue
            resumed = load_group(ck_path, mesh=grp.mesh)
            # the health flag is serve-run config, not checkpoint state:
            # the resumed instance dispatches the same program variant
            # the built group would have (ISSUE 6)
            resumed.health = getattr(grp, "health", False)
            # claimed extras resume when this run could have claimed them
            # (auto_register) OR when it serves frozen: an elastically-
            # learned fleet must be servable read-only from its own
            # checkpoint (--freeze forbids NEW claims — the footgun — but
            # not reading streams a prior learning run registered)
            validate_resume(resumed, ck_path, grp,
                            allow_claimed_extras=auto_register or not learn)
            groups[gi] = resumed  # n_live derives from the resumed ids
            # the registry's lookup() index must observe the resumed
            # instance too, not the stale fresh group
            if isinstance(group, StreamGroupRegistry):
                for slot in group._slots.values():
                    if slot.group is grp:
                        slot.group = resumed
                # streams the PRIOR run auto-registered (live in the
                # checkpoint, pads in the built group) rejoin the
                # registry's index so routing emits them and re-arriving
                # records aren't re-claimed into duplicate slots
                for si, sid in enumerate(resumed.stream_ids):
                    if not sid.startswith(PAD_PREFIX) and sid not in group:
                        group._slots[sid] = _RegistrySlot(resumed, si)
                        group.version += 1
            resumed_from[f"group{gi}"] = resumed.ticks
        # a checkpoint group BEYOND the built topology must not be
        # silently dropped: a run resumed with a smaller --reserve than
        # the one that learned (e.g. register-then-freeze without
        # repeating --reserve) would lose every stream living in the
        # extra groups — loudly demand a matching topology instead
        import re as _re

        stray = sorted(
            d for d in os.listdir(checkpoint_dir)
            if _re.fullmatch(r"group\d{4,}", d)
            and int(d[5:]) >= len(groups)
            and os.path.isdir(os.path.join(checkpoint_dir, d))
        ) if os.path.isdir(checkpoint_dir) else []
        if stray:
            raise ValueError(
                f"checkpoint dir {checkpoint_dir} holds {stray} beyond this "
                f"run's {len(groups)} group(s): the prior run had more "
                "claimable capacity. Rerun with the same --reserve/"
                "--group-size so every checkpointed stream resumes")
        if isinstance(group, StreamGroupRegistry) and resumed_from:
            # the source must accept the resumed extras' records and return
            # values in the (possibly grown) dispatch order / slot map
            _sync_source_membership(source, group)
        # A crash between per-group saves leaves a torn set (groups at
        # different ticks). Live data is NOT tick-indexed (every group
        # scores whatever arrives now) and groups are fully independent,
        # so a behind group merely lost a few ticks of learning — resume
        # anyway, loudly: the skew is warned and exposed in stats.
        # (replay_streams is different: its feed IS tick-indexed, and it
        # resumes each group from its own recorded offset.)
        ticks_seen = {g.ticks for g in groups}
        if len(ticks_seen) > 1:
            import logging

            logging.getLogger(__name__).warning(
                "live_loop: resuming a torn checkpoint set (group ticks %s "
                "— a crash landed between per-group saves); behind groups "
                "lost that many ticks of learning", sorted(ticks_seen))
        resume_tick_skew = (max(ticks_seen) - min(ticks_seen)) if resumed_from else 0
    reg = group if isinstance(group, StreamGroupRegistry) else None

    # Value/emission routing: per group, the live slot indices, their ids,
    # and the group's offset into the source value vector. Live slots are a
    # prefix for a freshly finalized registry, but dynamic membership
    # (claim/release of pad slots — SURVEY.md C19 lazy creation) makes them
    # an arbitrary subset, so routing is index-based, not slicing. Rebuilt
    # only when the registry's membership version changes; each in-flight
    # pipeline entry carries the routing it was dispatched under.
    def _build_routing():
        maps, off = [], 0
        for g in groups:
            slots = g.live_slots()
            maps.append((slots, [g.stream_ids[i] for i in slots], off))
            off += len(slots)
        if predictor is not None and predictor.blast is not None:
            # claimed streams join their cluster's predicted blast
            # radius as soon as they route (idempotent set union)
            predictor.blast.observe_streams(
                sid for _slots, ids, _off in maps for sid in ids)
        return maps, off

    routing, n_expected = _build_routing()
    routing_version = reg.version if reg is not None else 0
    # --- telemetry (rtap_tpu.obs): every hot-path observation below goes
    # through instruments cached here — creation is the cold path, emission
    # is lock-free per-thread cells (docs/TELEMETRY.md catalogs the names)
    obs = get_registry()
    obs_ticks = obs.counter(
        "rtap_obs_ticks_total", "live_loop ticks completed")
    obs_scored = _scored_counter()
    obs_tick_seconds = obs.histogram(
        "rtap_obs_tick_seconds",
        "per-tick host wall seconds (poll -> emit, excl. cadence sleep)")
    obs_phase = {
        p: obs.histogram(
            "rtap_obs_phase_seconds",
            "per-tick wall seconds by loop phase", phase=p)
        for p in _PHASES
    }
    obs_streams = obs.gauge(
        "rtap_obs_streams_active",
        "live (non-pad) stream slots currently routed")
    obs_streams.set(n_expected)
    obs_rebuilds = obs.counter(
        "rtap_obs_routing_rebuilds_total",
        "emission-routing rebuilds after membership version bumps")
    obs_last_tick_wall = obs.gauge(
        "rtap_obs_last_tick_unixtime",
        "wall-clock unix time the last tick completed — the GET /healthz "
        "liveness source (age > stale_after_s reads 503)")
    obs_warm_compiles = obs.counter(
        "rtap_obs_warm_compiles_total",
        "cold (chunk length, group config) programs dispatched serially "
        "to keep compiles single-flight")
    obs_dup_avoided = obs.counter(
        "rtap_obs_dup_compiles_avoided_total",
        "cold programs the pre-(m, config) warm-up keying would have "
        "compiled concurrently in N pool threads (ADVICE r5)")
    obs_trace_records = obs_trace_dropped = None
    if trace is not None:
        # span-ring health as gauges, set once per tick (the recorder has
        # no counters of its own — its hot path is a handful of stores)
        obs_trace_records = obs.gauge(
            "rtap_obs_trace_records",
            "span/instant records appended to the trace ring this run")
        obs_trace_dropped = obs.gauge(
            "rtap_obs_trace_dropped",
            "trace records overwritten by ring wrap-around (grow "
            "--trace-ring if postmortems need deeper history)")
    auto_registered = 0
    auto_rejected_total = 0
    auto_rejected: set = set()  # bounded de-dup memory, not the count
    auto_released = 0
    silent_ticks: dict = {}  # sid -> consecutive all-NaN ticks
    release_pending: set = set()
    if auto_release_after < 0:
        raise ValueError(
            f"auto_release_after must be >= 0; got {auto_release_after}")
    if auto_release_after and reg is None:
        raise ValueError("auto_release_after needs a StreamGroupRegistry")
    if slo is not None and latency is None:
        raise ValueError(
            "slo needs latency: the SLO tracker judges the latency "
            "tracker's observations (serve --slo requires --latency)")
    writer = AlertWriter(alert_path, flush_every=alert_flush_every,
                         attributor=attributor,
                         fence=lease.still_mine if lease is not None
                         else None,
                         correlator=correlator, latency=latency)
    correlator_resume = None
    if correlator is not None:
        # incident correlation (ISSUE 9, rtap_tpu/correlate/): incidents
        # ride the alert stream like watchdog events, and a large-blast
        # incident dumps a postmortem like a quarantine does
        if correlator.sink is None:
            correlator.sink = writer.emit_event
        if correlator.flight is None:
            correlator.flight = flight
        if alert_path is not None:
            # crash/replay safety: re-fold the sink tail BEFORE any
            # replay/live emission — already-delivered alerts re-enter
            # the windows from disk (their replays are suppressed
            # upstream), already-emitted incident ids seed the dedupe
            # set, and incidents that closed pre-crash without their
            # event line landing re-emit (exactly-once incident stream
            # across kill-9). The scan starts at the correlator's
            # persisted sidecar floor, NOT the checkpoints' alert
            # cursors: a checkpoint taken while a window was open has a
            # cursor past that window's earlier members, and a re-fold
            # missing them would hash a divergent incident_id.
            if correlator.sidecar_path is None:
                from rtap_tpu.service.shardpath import alert_sidecar_path

                correlator.sidecar_path = alert_sidecar_path(
                    alert_path, "corr")
            known = [off for off in (
                getattr(g, "resume_alerts_offset", None) for g in groups)
                if off is not None]
            correlator_resume = correlator.resume_from(
                alert_path,
                correlator.resume_scan_offset(min(known) if known else 0))
    if lease is not None:
        # freshness lives on the heartbeat thread (idempotent when the
        # caller already started it); the loop itself only DETECTS the
        # fence via the cached still_mine() probe — a per-tick
        # read+rewrite of the lease file has no place on the hot path
        lease.start_heartbeat()
    if resume_suppression:
        # a promoted standby hands over the alert ids its dead leader
        # delivered for ticks the standby never received: this loop will
        # re-score those ticks live, and the ids must suppress, not
        # duplicate (resilience/replicate.py StandbyFollower._promote)
        writer.arm_suppression(set(resume_suppression))
    fenced = False
    counter = ThroughputCounter()
    # ---- resilience wiring (rtap_tpu.resilience, docs/RESILIENCE.md) ----
    if chaos is not None:
        # injection OUTSIDE the loop's own code: the wrapped source and
        # alert file exercise the real recovery paths from below
        source = chaos.wrap_source(source)
        chaos.wrap_alert_writer(writer)

    def _sync_chaos_routing():
        """Tell the engine which source-vector slice each group reads, so
        group-targeted source faults hit exactly that group's streams.
        Re-synced after every routing rebuild."""
        if chaos is not None:
            chaos.set_group_streams({
                gi: tuple(range(off, off + len(slots)))
                for gi, (slots, _ids, off) in enumerate(routing)})

    _sync_chaos_routing()
    if degradation is not None and degradation.sink is None:
        degradation.sink = writer.emit_event
    if health is not None:
        # same wiring contract as the watchdog/degradation: incidents
        # ride the alert stream, and a health incident is a black-box
        # moment — the flight recorder dumps a postmortem for it, and
        # every bundle's summary embeds the latest scorecards
        if health.sink is None:
            health.sink = writer.emit_event
        if health.flight is None:
            health.flight = flight
        if flight is not None and flight.health_provider is None:
            flight.health_provider = health.snapshot
    if predictor is not None:
        # same wiring contract as the health tracker: precursor /
        # predicted_incident events ride the alert stream, request
        # postmortem dumps, and every bundle's summary embeds the
        # latest divergence scorecards
        if predictor.sink is None:
            predictor.sink = writer.emit_event
        if predictor.flight is None:
            predictor.flight = flight
        if flight is not None and flight.predict_provider is None:
            flight.predict_provider = predictor.snapshot
    if slo is not None:
        # SLO guardrail wiring (ISSUE 11, obs/slo.py): burn events ride
        # the alert stream, a fast burn dumps a postmortem, and the
        # latency tracker feeds it per observation
        if slo.sink is None:
            slo.sink = writer.emit_event
        if slo.flight is None:
            slo.flight = flight
        if latency.slo is None:
            latency.slo = slo
    if latency is not None and flight is not None \
            and flight.latency_provider is None:
        # every postmortem bundle's summary embeds the latest stage
        # waterfall + windowed quantiles (the slo_burn triage surface)
        flight.latency_provider = latency.snapshot
    eff_cadence = cadence_s  # widened by the degradation ladder's level 3
    quarantined: dict[int, dict] = {}  # gi -> {tick, phase, error, restore_at}
    quarantine_log: list[dict] = []  # full quarantine/restore history, in
    # stats: the chaos soak's verification oracle must not depend on the
    # alert stream (whose sink may itself be the faulted component)
    group_scored = [0] * len(groups)  # per-group scored samples (the chaos
    # soak's silent-gap check: a group's count must match its unquarantined
    # tick intervals exactly)
    _res_counters: dict = {}

    def _res_event(kind: str, tick: int, **fields) -> None:
        """Structured resilience event: one registry counter bump per kind
        + one JSONL line on the alert stream (same contract as watchdog
        events; docs/RESILIENCE.md catalogs the vocabulary)."""
        c = _res_counters.get(kind)
        if c is None:
            c = _res_counters[kind] = obs.counter(
                "rtap_obs_resilience_events_total",
                "structured resilience events by kind", event=kind)
        c.inc()
        if trace is not None:
            # same timeline as the phase spans: the quarantine/degrade
            # mark lands visually inside the span that raised it
            trace.add_instant(kind, int(tick), fields,
                              group=int(fields.get("group", -1)))
        if flight is not None:
            flight.record_event({"event": kind, "tick": int(tick), **fields})
        writer.emit_event({"event": kind, "tick": int(tick), **fields})

    obs_groups_quarantined = obs.gauge(
        "rtap_obs_groups_quarantined",
        "stream groups currently quarantined (dispatch/collect fault "
        "isolation)")
    obs_groups_quarantined.set(0)
    # control-plane degradation accounting: only armed when the lease is
    # control-plane-backed (ControlLease exposes ``degraded``); a file
    # lease never counts here
    obs_control_degraded = None
    control_degraded_ticks = 0
    if lease is not None and hasattr(lease, "degraded"):
        obs_control_degraded = obs.counter(
            "rtap_obs_control_degraded_ticks_total",
            "ticks served on the cached control-plane lease while the "
            "plane was unreachable (bounded by the degraded grace "
            "window; >0 after an outage proves no tick stalled)")
    obs_source_errors = obs.counter(
        "rtap_obs_source_errors_total",
        "source callables that RAISED (vs. returning NaN); the tick "
        "scored a whole-vector missing sample instead of dying")
    obs_ts_regressions = obs.counter(
        "rtap_obs_source_time_regressions_total",
        "ticks whose source timestamp went backwards (clamped monotonic)")

    def _quarantine_group(gi: int, tick: int, phase: str, exc: Exception):
        """Isolate a faulted group: it stops being dispatched/collected/
        emitted (and checkpointed — its state may be mid-chunk) while
        every other group keeps its cadence. In-flight handles for the
        group are left uncollected by the quarantine check in
        _collect_tick — after a failed dispatch/collect its seq chain is
        broken anyway."""
        if gi in quarantined:
            return
        info = {"tick": int(tick), "phase": phase,
                "error": f"{type(exc).__name__}: {exc}"}
        if quarantine_restore_after and checkpoint_dir is not None:
            info["restore_at"] = int(tick) + int(quarantine_restore_after)
        quarantined[gi] = info
        quarantine_log.append({"event": "group_quarantined", "group": gi,
                               "tick": int(tick), "phase": phase})
        obs_groups_quarantined.set(len(quarantined))
        _res_event("group_quarantined", tick, group=gi, phase=phase,
                   error=info["error"],
                   streams=int(groups[gi].n_live))
        if flight is not None:
            # the black-box moment: dump a postmortem bundle for this
            # isolation (queued; written after the tick's accounting)
            flight.request_dump("group_quarantined", tick)

    source_error_run = 0  # consecutive source raises (event on the first)
    last_ts_seen = None  # monotonic clamp floor for source timestamps
    ts_regress_run = 0  # consecutive clamped ticks (event on the first)
    # trailing value dims for the NaN substitute when the source raises.
    # Seeded from the model config, NOT discovered from the first good
    # poll: a multivariate source that raises on tick 0 would otherwise
    # get a [G]-shaped substitute where dispatch expects [G, n_fields],
    # and the shape error would quarantine EVERY group permanently.
    _nf = groups[0].cfg.n_fields if groups else 1
    fallback_trailing: tuple = (_nf,) if _nf > 1 else ()
    ck_breaker = None
    ck_quarantine_announced = False
    checkpoint_save_failures = 0
    if checkpoint_dir is not None:
        from rtap_tpu.resilience.policies import CircuitBreaker

        # 3 consecutive failed save ROUNDS quarantine checkpointing (the
        # disk is full — stop paying the drain+fetch+fail cost every
        # cadence); the cooldown admits a probe round later
        ck_breaker = CircuitBreaker(
            fail_threshold=3, cooldown_s=max(30.0, 10 * cadence_s),
            name="checkpoint")

    def _on_save_failure(gi: int, tick: int, exc: Exception) -> None:
        nonlocal checkpoint_save_failures
        checkpoint_save_failures += 1
        _res_event("checkpoint_save_failed", tick, group=gi,
                   error=f"{type(exc).__name__}: {exc}")
    # deadline/starvation/stall events -> registry counters + structured
    # JSONL lines on the alert stream (obs/watchdog.py)
    watchdog = TickWatchdog(cadence_s, registry=obs,
                            event_sink=writer.emit_event,
                            trace=trace, flight=flight)
    missed = 0
    checkpoints_saved = 0
    ticks_run = 0
    last_saved = 0
    latencies = np.empty(n_ticks, np.float64)  # per-tick poll->emit seconds
    # per-phase accounting (100k-soak forensics: the tick period pinned at
    # ~1.4 s independent of stream count AND group count — the breakdown
    # names the binding phase instead of guessing). Wall seconds summed
    # over the run; reported per tick in stats["phase_ms_per_tick"].
    phase_s = {"source": 0.0, "membership": 0.0, "dispatch": 0.0,
               "collect": 0.0, "emit": 0.0, "checkpoint": 0.0}

    # one pool for the whole loop (threads are cheap to keep, expensive to
    # respawn per tick); None = the serial schedule, bit-identical by test
    pool = None
    eff_threads = 1  # effective worker count, reported in stats
    if dispatch_threads > 1 and len(groups) > 1:
        from concurrent.futures import ThreadPoolExecutor

        eff_threads = min(dispatch_threads, len(groups))
        pool = ThreadPoolExecutor(max_workers=eff_threads,
                                  thread_name_prefix="rtap-loop-dispatch")

    cur_tick = 0  # the loop's tick clock, read by the fault-capture paths

    def _try_collect(item):
        """Collect one group's chunk, capturing the fault instead of
        letting it escape a pool thread: (gi, result-or-None, exc-or-None).
        Quarantine itself happens after the join, in the loop thread —
        AlertWriter emission is single-threaded by contract."""
        gi, grp, h = item
        tg0 = time.perf_counter() if trace is not None else 0.0
        try:
            if chaos is not None:
                chaos.on_collect(gi, cur_tick)
            return gi, grp.collect_chunk(h), None
        except Exception as e:  # noqa: BLE001 — any fault isolates the group
            return gi, None, e
        finally:
            if trace is not None:
                # per-group child span on the group's own track — runs in
                # a pool thread; the recorder's shards are per-thread
                trace.add_span("collect", cur_tick, tg0,
                               time.perf_counter() - tg0, group=gi)

    def _collect_tick(ts_rows, value_rows, handles, rmaps, idx=None):
        # collects in parallel (each blocks on its group's device fetch —
        # the per-group RPC on a remote link), emission strictly serial in
        # group order so the alert stream is schedule-independent. `idx`
        # restricts to a subset of groups (chunk_stagger phase classes).
        # Quarantined groups (and handles their failed dispatch left None)
        # are skipped; a collect fault quarantines its group here and the
        # rest of the tick proceeds untouched.
        sel = range(len(groups)) if idx is None else idx
        t0 = time.perf_counter()
        pairs = [(gi, groups[gi], h) for gi, h in zip(sel, handles)
                 if gi not in quarantined and h is not None]
        if pool is None:
            outs = [_try_collect(p) for p in pairs]
        else:
            outs = list(pool.map(_try_collect, pairs))
        t1 = time.perf_counter()
        phase_s["collect"] += t1 - t0
        if trace is not None:
            trace.add_span("collect", cur_tick, t0, t1 - t0)
        results: dict = {}
        for gi, res, exc in outs:
            if exc is not None:
                _quarantine_group(gi, cur_tick, "collect", exc)
            else:
                results[gi] = res
        scored = 0
        for gi, _grp, _h in pairs:  # pairs preserve group order (emission
            if gi not in results:  # stays schedule-independent)
                continue
            raw, loglik, alerts = results[gi]
            slots, ids, off = rmaps[gi]
            n = len(slots)
            # the group's own tick counter names the rows just collected
            # (collect_chunk already advanced it by the chunk length):
            # alert_id = group:stream:group-tick is stable across restarts
            # and identical to an uninterrupted run's. A mid-run
            # quarantine restore rewinds the counter — its epoch suffix
            # keeps the rewound timeline's ids collision-free.
            grp_tick0 = groups[gi].ticks - len(ts_rows)
            gid = _alert_gid(gi, groups[gi])
            for i, (ts, values) in enumerate(zip(ts_rows, value_rows)):
                writer.emit_batch(ids, np.full(n, ts), values[off:off + n],
                                  raw[i, slots], loglik[i, slots],
                                  alerts[i, slots], group=gid,
                                  tick=grp_tick0 + i)
                counter.add(n)
                scored += n
            group_scored[gi] += len(ts_rows) * n
            if health is not None and groups[gi].last_health is not None:
                # fold the chunk's fused health leaves into the group's
                # scorecard (one call per collected chunk per group; the
                # tracker's own cost is gated by bench.py --obs-bench)
                health.fold(gi, groups[gi].last_health, tick=cur_tick)
            if predictor is not None \
                    and groups[gi].last_predict is not None:
                # fold the chunk's fused predict leaves into the per-
                # stream divergence trajectories; slot -> id mapping
                # rides the same routing snapshot the emission used, so
                # precursor events page with live stream ids. The fold
                # keys on the GROUP tick (the counter checkpoints
                # carry, = the chunk's last row), NOT the loop-local
                # cur_tick: precursor ids must reproduce across a
                # restart + journal replay for resume suppression
                id_by_slot = [None] * groups[gi].G
                for s, sid in zip(slots, ids):
                    id_by_slot[s] = sid
                predictor.fold(gi, groups[gi].last_predict,
                               tick=groups[gi].ticks - 1,
                               ids=id_by_slot)
        obs_scored.inc(scored)
        if journal is not None and pairs:
            # alert-delivery cursor: alerts through this tick have been
            # handed to the sink at this byte offset. A hot standby
            # PRUNES its buffered alert lines on this record (ISSUE 8),
            # so the offset must never point past bytes still sitting
            # in the stdio buffer — flush first (no-op at the
            # flush-per-batch default; with --alert-flush-every N the
            # journal pins an every-tick flush, or a kill would lose
            # alerts the standby already counted as delivered)
            writer.flush_sink()
            journal.append_cursor(journal_base + cur_tick,
                                  writer.sink_offset())
        t2 = time.perf_counter()
        phase_s["emit"] += t2 - t1
        if trace is not None:
            trace.add_span("emit", cur_tick, t1, t2 - t1)

    aot_programs = 0
    if aot_warmup:
        # compile every knowable (chunk length, config, learn) program —
        # and the first-claim realignment program — BEFORE tick 0, so no
        # XLA compile can land inside a scored tick (service/aot.py; the
        # 1h 100k soak's 9 missed deadlines were all warm-up compiles)
        from rtap_tpu.service.aot import prewarm

        prewarmed = prewarm(
            groups, micro_chunk, learn, degradation=degradation,
            include_claim=auto_register or any(
                g.free_slot_count() for g in groups))
        aot_programs = len(prewarmed)
    else:
        prewarmed = set()

    warmed: set = set(prewarmed)  # (chunk length m, group config, learn flag)
    # programs already dispatched once: the first dispatch of each PROGRAM
    # runs serially — concurrent cold misses on step.py's compiled-fn
    # lru_cache are not single-flight, so N pool threads would each
    # trace+compile the same program (up to Nx the dominant startup cost
    # over the tunnel). Programs are cached per ModelConfig, and
    # stagger_learn gives groups DISTINCT learn_phase configs — keying by
    # m alone (the pre-r5-ADVICE heuristic) let a later phase class's
    # first flush at an already-seen m cold-compile concurrently in every
    # pool thread. The learn flag is part of the key too: learn=True and
    # learn=False trace distinct programs, and the degradation ladder's
    # score_only step flips it mid-run. chunk_stagger's ramp-in dispatches
    # m=1..M chunks, each a distinct program, so warm-up is per
    # (m, config, learn), never once.
    seen_m: set = set()  # what the old m-only heuristic would have warmed:
    # a cold program at an already-seen m is exactly a duplicate compile
    # the old keying would NOT have serialized — counted as avoided

    # ---- journal recovery + replay (resilience/journal.py, ISSUE 5) ----
    # The write-ahead journal holds every tick row ingested since the
    # oldest live checkpoint. Replay each recovered row past a group's
    # checkpoint tick through the normal per-group dispatch/collect path
    # (m=1 chunks — the same programs, bit-identical results), emitting
    # alerts under the resume suppression set so already-delivered ids
    # are never duplicated and never lost. No cadence: catch-up runs as
    # fast as the chip allows, and its wall cost is reported.
    journal_replay = {"replayed_ticks": 0, "replay_seconds": 0.0,
                      "skipped_rows": 0}
    gpos: list = []
    if journal is not None:
        t_jr0 = time.perf_counter()
        if chaos is not None:
            # replay is RECOVERY, not live serving: no fault window may
            # apply to it (a shifted sink fault at local tick 0 would
            # otherwise drop replayed alerts — permanently, breaking
            # exactly-once). No Fault window can cover tick -1.
            chaos.set_tick(-1)
        # per-group GLOBAL journal cursor: where in the global tick
        # stream each group's checkpoint stopped. Equals the group's own
        # counter on its original timeline, but a mid-run quarantine
        # restore REWINDS the counter while the global clock keeps
        # running — matching rows by grp.ticks would then feed a
        # restored group the wrong rows (or falsely gap-quarantine it),
        # so the save path records the global cursor in meta.
        gpos = [
            grp.resume_journal_tick
            if getattr(grp, "resume_journal_tick", None) is not None
            else grp.ticks
            for grp in groups
        ]
        jrows = [r for r in journal.recovered_ticks
                 if r[0] >= min(gpos, default=0)]
        if journal.truncations or journal.dropped_segments:
            # the torn tail was truncated at construction — say so on
            # the incident stream (counted, never a refusal to start)
            _res_event("journal_tail_truncated", 0,
                       truncations=int(journal.truncations),
                       bytes=int(journal.truncated_bytes),
                       dropped_segments=int(journal.dropped_segments))
        if jrows:
            if alert_path is not None:
                # exactly-once: every alert byte past the checkpoints'
                # alert cursors belongs to the ticks about to be
                # replayed — suppress exactly those ids
                from rtap_tpu.service.alerts import scan_alert_ids

                known_offs = [
                    off for off in (
                        getattr(g, "resume_alerts_offset", None)
                        for g in groups)
                    if off is not None]
                writer.arm_suppression(scan_alert_ids(
                    alert_path, min(known_offs) if known_offs else 0))
                if predictor is not None:
                    # precursor/predicted_incident ids are pure
                    # functions of (stream, group tick), so the replay
                    # below reproduces them — arm the tracker's own
                    # suppression so the replayed folds re-latch state
                    # without paging twice
                    from rtap_tpu.service.alerts import scan_event_ids

                    predictor.arm_suppression(scan_event_ids(
                        alert_path,
                        min(known_offs) if known_offs else 0))
            obs_jr = obs.counter(
                "rtap_obs_journal_replayed_ticks_total",
                "journaled ticks replayed through the scoring path on "
                "resume (crash catch-up)")
            gap_groups: set = set()  # groups whose replay window has a
            # hole (compacted/evicted rows): healing is impossible, and
            # scoring row jt as some earlier tick would SILENTLY corrupt
            # state and alert ids — skip the group loudly instead
            jtable = None  # dispatch table for FRAME records, built once
            from rtap_tpu.resilience.journal import JournaledFrames

            for jt, jts, jvals in jrows:
                if isinstance(jvals, JournaledFrames):
                    # binary-ingest tick: materialize the row by re-
                    # running the ingest scatter over the raw frames
                    # (bit-exact; valid because membership changes
                    # checkpoint + compact at their boundary)
                    if jvals.width != n_expected or reg is None:
                        journal_replay["skipped_rows"] += 1
                        continue
                    from rtap_tpu.ingest.dispatch import (
                        DispatchTable,
                        decode_frames_to_row,
                    )

                    if jtable is None:
                        jtable = DispatchTable.from_registry(reg)
                    jvals = decode_frames_to_row(
                        [jvals.blob], jvals.width, jtable)
                else:
                    jvals = np.asarray(jvals, np.float32)
                if len(jvals) != n_expected:
                    # membership changed between record and resume —
                    # normally impossible: every membership change
                    # checkpoints + compacts at its drained boundary
                    # (the routing-rebuild block below), so a surviving
                    # mismatch means the change ran without a
                    # --checkpoint-dir; skip the row (counted)
                    journal_replay["skipped_rows"] += 1
                    continue
                for gi, grp in enumerate(groups):
                    if gi in quarantined or gi in gap_groups \
                            or gpos[gi] > jt:
                        continue  # this group's checkpoint is already past
                    if jt > gpos[gi]:
                        # QUARANTINE, not just an event: a gap group
                        # resuming live at its stale counter would score
                        # fresh rows as the wrong ticks and reuse
                        # already-delivered alert ids — the exact
                        # corruption the journal exists to prevent
                        gap_groups.add(gi)
                        _quarantine_group(gi, 0, "journal_replay_gap",
                                          RuntimeError(
                                              f"journal gap: group "
                                              f"resumes at global tick "
                                              f"{gpos[gi]} but the "
                                              f"first surviving row is "
                                              f"tick {jt} (compacted/"
                                              "evicted)"))
                        continue
                    slots, g_ids, off = routing[gi]
                    v = np.full((1, grp.G) + jvals.shape[1:], np.nan,
                                np.float32)
                    v[0, slots] = jvals[off:off + len(slots)]
                    t = np.full((1, grp.G), int(jts), np.int64)
                    key = (1, grp.cfg, learn)
                    if key not in warmed:
                        warmed.add(key)
                        obs_warm_compiles.inc()
                    try:
                        r_raw, r_ll, r_al = grp.collect_chunk(
                            grp.dispatch_chunk(v, t, learn=learn))
                    except Exception as e:  # noqa: BLE001 — isolate group
                        _quarantine_group(gi, jt, "journal_replay", e)
                        continue
                    gpos[gi] += 1
                    if health is not None and grp.last_health is not None:
                        # catch-up ticks warm the scorecards/EWMAs too:
                        # the resumed fleet reaches the live edge with
                        # its drift baseline intact, not cold. Tick 0,
                        # like every other replay-time event (_res_event
                        # journal_replayed): the live loop folds with
                        # LOCAL ticks, and a global-tick fold here would
                        # park the flight recorder's per-reason dump
                        # throttle thousands of ticks in the future
                        health.fold(gi, grp.last_health, tick=0)
                    if predictor is not None \
                            and grp.last_predict is not None:
                        # predictor folds key on the GROUP tick — the
                        # counter the checkpoints carry — so a replayed
                        # fold reproduces the pre-crash precursor ids
                        # exactly and the suppression set armed above
                        # catches them (unlike health, whose fold tick
                        # is only dump-throttle metadata)
                        id_by_slot = [None] * grp.G
                        for s, sid in zip(slots, g_ids):
                            id_by_slot[s] = sid
                        predictor.fold(gi, grp.last_predict,
                                       tick=grp.ticks - 1,
                                       ids=id_by_slot)
                    n = len(slots)
                    writer.emit_batch(
                        g_ids, np.full(n, int(jts)), jvals[off:off + n],
                        r_raw[0, slots], r_ll[0, slots], r_al[0, slots],
                        group=_alert_gid(gi, grp), tick=grp.ticks - 1)
                    counter.add(n)
                    obs_scored.inc(n)
                obs_jr.inc()
                if correlator is not None:
                    # the correlation clock advances on the REPLAYED
                    # stream's own timestamps, so every close decision
                    # reproduces the uninterrupted run's bit-for-bit
                    correlator.on_tick(int(jts))
                last_ts_seen = int(jts) if last_ts_seen is None \
                    else max(last_ts_seen, int(jts))
            journal_replay["replayed_ticks"] = \
                len(jrows) - journal_replay["skipped_rows"]
            if gap_groups:
                journal_replay["gap_groups"] = sorted(gap_groups)
            journal_replay["replay_seconds"] = round(
                time.perf_counter() - t_jr0, 4)
            _res_event("journal_replayed", 0,
                       ticks=journal_replay["replayed_ticks"],
                       from_tick=int(jrows[0][0]), to_tick=int(jrows[-1][0]),
                       seconds=journal_replay["replay_seconds"])
        del jrows
        journal.release_recovered()  # a large replay window must not
        # stay resident for the rest of the run (counts live in stats)
    # the run's global tick base: journal records and cursors are indexed
    # past every global position already reached AND every index already
    # on disk (0 on a fresh start). The next_tick floor matters when
    # every group gap-quarantined: appends must never reuse an existing
    # index, so recovery's keep-first-copy dedup stays unambiguous.
    journal_base = max(gpos + [journal.next_tick]) \
        if journal is not None else 0

    def _try_dispatch(gi, grp, v, t, learn_flag):
        """Dispatch one group's chunk, capturing the fault: a raising
        dispatch (device error, wedged RPC surfacing, injected chaos)
        must isolate THAT group, not unwind the tick."""
        tg0 = time.perf_counter() if trace is not None else 0.0
        try:
            if chaos is not None:
                chaos.on_dispatch(gi, cur_tick)
            return grp.dispatch_chunk(v, t, learn=learn_flag), None
        except Exception as e:  # noqa: BLE001 — any fault isolates the group
            return None, e
        finally:
            if trace is not None:
                trace.add_span("dispatch", cur_tick, tg0,
                               time.perf_counter() - tg0, group=gi)

    def _dispatch_all(value_rows, ts_rows, rmaps, idx=None, learn_flag=None):
        """Dispatch every non-quarantined group in `idx`; returns handles
        ALIGNED WITH `idx` (None for quarantined/faulted groups, which
        _collect_tick skips). A dispatch fault quarantines its group after
        the pool joins (loop-thread-only emission)."""
        if learn_flag is None:
            learn_flag = learn
        sel = list(range(len(groups))) if idx is None else list(idx)
        m = len(value_rows)
        handles: list = [None] * len(sel)
        staged = []  # (handle slot j, gi, grp, v, t)
        for j, gi in enumerate(sel):
            if gi in quarantined:
                continue
            grp = groups[gi]
            slots, _ids, off = rmaps[gi]
            # trailing field axis preserved: values may be [G] or [G, n_fields]
            v = np.full((m, grp.G) + value_rows[0].shape[1:], np.nan,
                        np.float32)
            for i, row in enumerate(value_rows):
                v[i, slots] = row[off:off + len(slots)]
            t = np.repeat(np.asarray(ts_rows, np.int64)[:, None], grp.G,
                          axis=1)
            staged.append((j, gi, grp, v, t))
        faults: list = []
        if pool is None:
            for j, gi, grp, v, t in staged:
                key = (m, grp.cfg, learn_flag)
                if key not in warmed:
                    warmed.add(key)
                    obs_warm_compiles.inc()
                handles[j], exc = _try_dispatch(gi, grp, v, t, learn_flag)
                if exc is not None:
                    faults.append((gi, exc))
            seen_m.add(m)
        else:
            # pooled path: dispatch each COLD (m, config, learn) program
            # serially once (the dispatch call blocks through
            # trace+compile, so the cache is warm before any thread can
            # race it); same-program and warm groups overlap in the pool
            pooled: list = []
            for j, gi, grp, v, t in staged:
                key = (m, grp.cfg, learn_flag)
                if key not in warmed:
                    warmed.add(key)
                    obs_warm_compiles.inc()
                    if m in seen_m:
                        obs_dup_avoided.inc()
                    handles[j], exc = _try_dispatch(gi, grp, v, t, learn_flag)
                    if exc is not None:
                        faults.append((gi, exc))
                else:
                    pooled.append((j, gi, grp, v, t))
            seen_m.add(m)
            if pooled:
                outs = list(pool.map(
                    lambda it: _try_dispatch(it[1], it[2], it[3], it[4],
                                             learn_flag),
                    pooled))
                for (j, gi, _grp, _v, _t), (h, exc) in zip(pooled, outs):
                    handles[j] = h
                    if exc is not None:
                        faults.append((gi, exc))
        for gi, exc in faults:
            _quarantine_group(gi, cur_tick, "dispatch", exc)
        return handles

    # Cross-tick pipeline (pipeline_depth=2): collect tick k-1 AFTER
    # dispatching tick k, so the device round trip — which over the remote-
    # chip tunnel costs ~65 ms per group per tick and made the 16x256
    # production soak miss EVERY 1 s deadline (reports/live_soak.json,
    # p50 1.07 s) — overlaps the cadence sleep instead of the tick budget.
    # The price is results lagging one tick (alert latency +1 cadence),
    # stated in the stats via "pipeline_depth". Depth 1 keeps the
    # dispatch-collect-emit-same-tick behavior.
    # chunk_stagger: group i belongs to phase class i mod M; each class
    # keeps its own buffer + pipeline and flushes on ITS boundary (class
    # c's first chunk is c+1 rows, then every M) — so each tick dispatches
    # ~1/M of the fleet instead of the whole fleet every M-th tick,
    # leveling the boundary-tick spike the plain micro_chunk path carries
    # (r5 steady soak: 2.8 s of chunk work on one tick = a guaranteed
    # miss). Plain mode is the single class 0.
    n_classes = micro_chunk if chunk_stagger else 1
    class_idx = [
        [i for i in range(len(groups)) if i % n_classes == c]
        for c in range(n_classes)
    ]
    in_flights: list[deque] = [deque() for _ in range(n_classes)]
    chunk_bufs: list[list] = [[] for _ in range(n_classes)]
    first_flush_done = [False] * n_classes

    def _drain_all():
        for c in range(n_classes):
            while in_flights[c]:
                _collect_tick(*in_flights[c].popleft())

    def _align_boundaries():
        """Force a global nothing-buffered, nothing-in-flight instant.

        Rotating per-class boundaries never reach one naturally, but
        membership changes and periodic checkpoints need it (claims
        resize the source vector and reroute emission; saves must match
        the last collected tick). Flush every class's partial buffer,
        drain, and reset the ramp so boundaries re-stagger. Under
        chunk_stagger the partial sizes 1..M are the programs the ramp-in
        already compiled (warm); plain micro_chunk callers normally reach
        here with empty buffers (in-loop membership defers to a natural
        boundary), EXCEPT an out-of-band registry version bump, which
        forces a partial flush — a one-off cold compile of that chunk
        size, single-flighted by the (m, config) warm-up keying — rather
        than dying on the source-length check (ADVICE r5). Cost: one
        spiky tick per membership/checkpoint batch — fine for churn at
        tens-of-seconds cadence, wrong for per-tick churn."""
        for c in range(n_classes):
            if chunk_bufs[c]:
                _flush_class(c)
        _drain_all()
        if chunk_stagger:
            for c in range(n_classes):
                first_flush_done[c] = False

    def _flush_class(c):
        vrows = [b[0] for b in chunk_bufs[c]]
        tsrows = [b[1] for b in chunk_bufs[c]]
        chunk_bufs[c].clear()
        first_flush_done[c] = True
        if not class_idx[c]:
            return  # more classes than groups: nothing to dispatch
        # the degradation ladder removes learning per-chunk at dispatch
        # time (level 1 thins, level >= 2 freezes); it never adds it
        lrn = learn and (degradation is None
                         or degradation.learn_allowed(cur_tick))
        now = time.perf_counter()
        handles = _dispatch_all(vrows, tsrows, routing, class_idx[c],
                                learn_flag=lrn)
        t1 = time.perf_counter()
        phase_s["dispatch"] += t1 - now
        if trace is not None:
            trace.add_span("dispatch", cur_tick, now, t1 - now)
        in_flights[c].append((tsrows, vrows, handles, routing, class_idx[c]))
        while len(in_flights[c]) >= pipeline_depth:
            _collect_tick(*in_flights[c].popleft())
    try:
        for k in range(n_ticks):
            # orderly shutdown (SIGTERM -> serve's handler sets the event):
            # finish cleanly between ticks, save final state, report stats —
            # an evicted service must not lose since-last-checkpoint learning
            if stop_event is not None and stop_event.is_set():
                break
            if lease is not None:
                # lease-lifecycle events queued by the backend (control
                # plane lost/regained, drain marks) land in the same
                # counters/trace/alert-stream pipe as every other
                # resilience event — the loop stays backend-agnostic
                pop = getattr(lease, "pop_events", None)
                if pop is not None:
                    for ev_kind, ev_fields in pop():
                        _res_event(ev_kind, k, **ev_fields)
                if obs_control_degraded is not None \
                        and getattr(lease, "degraded", False):
                    # the cached-lease path, exercised: this tick runs
                    # without a reachable control plane
                    obs_control_degraded.inc()
                    control_degraded_ticks += 1
            if lease is not None and not lease.still_mine():
                # fenced: a standby promoted past our epoch while this
                # process was paused/partitioned. Stop scoring AND stop
                # emitting (the writer's fence already refuses) — the
                # new leader owns the stream; our unsaved ticks are its
                # journal's to replay, not ours to double-deliver.
                fenced = True
                pop = getattr(lease, "pop_events", None)
                if pop is not None:
                    # the probe that discovered the fence may have queued
                    # its own story (grace exhausted): flush it first
                    for ev_kind, ev_fields in pop():
                        _res_event(ev_kind, k, **ev_fields)
                _res_event("leader_fenced", k,
                           epoch=int(getattr(lease, "epoch", -1)),
                           holder=str(lease.holder() or ""))
                break
            cur_tick = k
            if chaos is not None:
                chaos.set_tick(k)
            t_start = time.perf_counter()
            t_phase = t_start
            scored_tick0 = list(group_scored) if flight is not None else None
            phase_tick0 = dict(phase_s)  # per-tick deltas feed the per-
            # phase histograms at tick end (cumulative sums stay the
            # source of truth for the membership-exclusion arithmetic)
            # membership booking excludes collect/emit/dispatch seconds
            # its drains and forced flushes accrue (those book into their
            # own phases; double-counting would mis-name the binding
            # phase — the instrumentation's job). Captured BEFORE the
            # restore block below: a restore's boundary-align drain books
            # into dispatch/collect, not membership.
            ce_tick0 = (phase_s["collect"] + phase_s["emit"]
                        + phase_s["dispatch"])
            # quarantine auto-restore (docs/RESILIENCE.md): a group whose
            # cooldown elapsed re-loads from its last checkpoint — losing
            # the ticks since that save, keeping every other group's
            # cadence. Books into the membership phase (it IS a membership
            # change: the group's model state is replaced wholesale).
            if quarantined and quarantine_restore_after:
                due = sorted(
                    gi for gi, info in quarantined.items()
                    if info.get("restore_at") is not None
                    and k >= info["restore_at"])
                if due:
                    import os

                    from rtap_tpu.service.checkpoint import (
                        load_group,
                        validate_resume,
                    )
                    from rtap_tpu.service.shardpath import (
                        group_checkpoint_path,
                    )

                    _align_boundaries()
                    restored_any = False
                    for gi in due:
                        ck_path = group_checkpoint_path(
                            checkpoint_dir, gi)
                        old = groups[gi]
                        try:
                            if not os.path.isdir(ck_path):
                                raise FileNotFoundError(
                                    f"no checkpoint at {ck_path} (the group "
                                    "was never saved before its fault)")
                            restored = load_group(ck_path, mesh=old.mesh)
                            restored.health = getattr(old, "health", False)
                            validate_resume(
                                restored, ck_path, old,
                                allow_claimed_extras=auto_register
                                or not learn)
                        except Exception as e:  # noqa: BLE001
                            # give up LOUDLY and stop retrying: restore is
                            # best-effort, quarantine is the safe state
                            quarantined[gi]["restore_at"] = None
                            quarantine_log.append(
                                {"event": "group_restore_failed",
                                 "group": gi, "tick": int(k)})
                            _res_event("group_restore_failed", k, group=gi,
                                       error=f"{type(e).__name__}: {e}")
                            continue
                        # the restore REWINDS the group's tick counter:
                        # bump its alert-id epoch so re-used tick
                        # indices never collide with already-delivered
                        # ids on the stream (downstream dedupe contract)
                        restored.alert_epoch = max(
                            restored.alert_epoch,
                            getattr(old, "alert_epoch", 0)) + 1
                        groups[gi] = restored
                        if reg is not None:
                            for slot in reg._slots.values():
                                if slot.group is old:
                                    slot.group = restored
                        del quarantined[gi]
                        restored_any = True
                        quarantine_log.append(
                            {"event": "group_restored", "group": gi,
                             "tick": int(k),
                             "resumed_from_tick": int(restored.ticks)})
                        obs_groups_quarantined.set(len(quarantined))
                        _res_event("group_restored", k, group=gi,
                                   resumed_from_tick=int(restored.ticks))
                    if restored_any:
                        # the restored instances replace groups[gi]: the
                        # routing maps hold per-group slot/id snapshots
                        # and must observe the new objects' membership
                        routing, n_expected = _build_routing()
                        routing_version = reg.version if reg is not None \
                            else 0
                        _sync_chaos_routing()
                        obs_rebuilds.inc()
                        obs_streams.set(n_expected)
                        if reg is not None:
                            _sync_source_membership(source, reg)
            # lazy model creation (serve --auto-register, SURVEY.md C19):
            # unknown ids the TCP listener saw claim free pad slots. The
            # pipeline drains first — membership may only change with
            # nothing in flight (a claimed slot's reset must not race a
            # dispatched-but-uncollected tick's emission routing).
            if auto_register and reg is not None \
                    and (not any(chunk_bufs) or chunk_stagger) \
                    and hasattr(source, "drain_unknown"):
                # filter ids that registered meanwhile (records arriving
                # between a drain and set_ids re-enter the unknown set) and
                # pad-prefixed ids (one malicious "__pad0" record must not
                # crash the server via claim_slot's reserved-prefix guard)
                fresh = [s for s in source.drain_unknown()
                         if s not in auto_rejected and s not in reg
                         and not s.startswith(PAD_PREFIX)]
                if fresh:
                    claimed = False
                    for sid in fresh:
                        if reg.free_slots == 0:
                            # remembered, not retried (capacity is static
                            # until a release) — bounded: an id-spraying
                            # producer must not grow host memory (the same
                            # threat MAX_UNKNOWN_TRACKED guards)
                            auto_rejected_total += 1
                            if len(auto_rejected) < _MAX_REJECTED_TRACKED:
                                auto_rejected.add(sid)
                            continue
                        if not claimed:
                            # membership may only change with nothing
                            # buffered or in flight (a claimed slot's
                            # reset must not race an uncollected tick's
                            # emission routing, and buffered rows carry
                            # the OLD vector length)
                            _align_boundaries()
                            claimed = True
                        reg.add_stream(sid)
                        auto_registered += 1
                    if claimed:
                        _sync_source_membership(source, reg)
            # elastic shrink (serve --auto-release-after): streams silent
            # for N consecutive ticks release their slots back to claimable
            # capacity — a churning monitored cluster (nodes leaving) must
            # not exhaust slots. A released stream that pushes again
            # re-registers as a NEW model (correct lazy semantics: the old
            # temporal context is stale by then anyway). Processed at the
            # top of the tick, like claims, under the same drain rule.
            if release_pending and (not any(chunk_bufs) or chunk_stagger):
                _align_boundaries()
                for sid in release_pending:
                    if sid in reg:
                        reg.remove_stream(sid)
                        silent_ticks.pop(sid, None)
                        auto_released += 1
                release_pending.clear()
                # capacity changed: previously rejected ids deserve a
                # retry (their records will re-surface as unknown) — a
                # leave-then-join churn must converge, not blacklist
                auto_rejected.clear()
                _sync_source_membership(source, reg)
            if reg is not None and reg.version != routing_version:
                # a version bump outside the blocks above (external claim/
                # release between ticks) still needs the aligned instant:
                # buffered rows were polled under the old routing. Plain
                # micro_chunk FORCES a partial flush here (ADVICE r5:
                # deferring to a natural boundary let an external actor
                # resize the source mid-chunk and die on the length check
                # next tick) — the one-off cold compile of the partial
                # chunk size is accepted and single-flighted by the
                # (m, config) warm-up keying above.
                _align_boundaries()
                routing, n_expected = _build_routing()
                routing_version = reg.version
                _sync_chaos_routing()
                obs_rebuilds.inc()
                obs_streams.set(n_expected)
                if journal is not None and checkpoint_dir and learn:
                    # a membership change resizes the journal's row
                    # width: checkpoint NOW (the pipeline is drained)
                    # so the replay window never spans two widths —
                    # otherwise a crash after a claim would skip the
                    # post-claim rows as width-mismatched and gap-
                    # quarantine the fleet on restart
                    writer.flush_sink()
                    _saved_m, failed_m = _save_all(
                        groups, checkpoint_dir, skip=quarantined,
                        chaos=chaos, tick=k,
                        on_failure=lambda gi, e: _on_save_failure(
                            gi, k, e),
                        alerts_offset=writer.sink_offset(),
                        journal_tick=journal_base + ticks_run)
                    if not failed_m:
                        checkpoints_saved += 1
                        last_saved = ticks_run
                        if not quarantined:
                            journal.compact(min(
                                (g.ticks for g in groups), default=0))
            now = time.perf_counter()
            _mem_booked = (now - t_phase) - (
                phase_s["collect"] + phase_s["emit"] + phase_s["dispatch"]
                - ce_tick0)
            phase_s["membership"] += _mem_booked
            if trace is not None and _mem_booked > 1e-6:
                # positioned at the block start with the BOOKED duration
                # (drains inside the block already own their own spans)
                trace.add_span("membership", k, t_phase,
                               max(0.0, _mem_booked))
            tick_frames = None  # raw binary ingest frames (journal path)
            try:
                values, ts = source(k)
            except Exception as e:  # noqa: BLE001
                # a RAISING source (connection drop, garbage payload the
                # adapter didn't absorb) must not kill scoring: the tick
                # becomes a whole-vector missing sample — the NaN path the
                # encoder already handles — counted, and evented on the
                # first raise of a consecutive run (the counter keeps
                # counting; the starvation watchdog narrates a long outage)
                obs_source_errors.inc()
                source_error_run += 1
                if source_error_run == 1:
                    _res_event("source_error", k,
                               error=f"{type(e).__name__}: {e}")
                values = np.full((n_expected,) + fallback_trailing, np.nan,
                                 np.float32)
                # stay on the SOURCE's timeline, not the host's: a wall
                # clock ahead of the feed's timestamps would pin the
                # monotonic clamp below and freeze ts for the whole run
                ts = last_ts_seen if last_ts_seen is not None \
                    else int(time.time())
            else:
                source_error_run = 0
                if journal is not None and hasattr(source,
                                                   "take_tick_frames"):
                    # only a SUCCESSFUL poll may journal raw frames —
                    # the fallback NaN tick below must journal as the
                    # full-width NaN row it actually scored
                    tick_frames = source.take_tick_frames()
            _src_t1 = time.perf_counter()
            phase_s["source"] += _src_t1 - now
            if trace is not None:
                trace.add_span("source", k, now, _src_t1 - now)
            # the poll-done wall instant anchors the tick's ingest-lag
            # measurement (source ts -> loop); perf_counter has no epoch
            lat_poll_wall = time.time() if latency is not None else 0.0
            values = np.asarray(values, np.float32)
            watchdog.observe_source(k, values)
            if len(values) != n_expected:
                raise ValueError(
                    f"source returned {len(values)} values for {n_expected} "
                    "live streams (alignment with registration order is load-"
                    "bearing — a silent mismatch would misroute streams)")
            fallback_trailing = values.shape[1:]
            # timestamps must not run backwards into the models' date
            # encodings (a misbehaving exporter clock): clamp monotonic
            # non-decreasing, count, and event the first regression of a run
            ts = int(ts)
            if last_ts_seen is not None and ts < last_ts_seen:
                obs_ts_regressions.inc()
                if ts_regress_run == 0:
                    _res_event("source_time_regression", k, ts=ts,
                               clamped_to=last_ts_seen)
                ts_regress_run += 1
                ts = last_ts_seen
            else:
                ts_regress_run = 0
                last_ts_seen = ts
            if journal is not None:
                # the write-ahead moment: the row is durable (flushed to
                # the kernel; fsync per policy) BEFORE any scoring — a
                # death past this point replays this tick on restart.
                # Binary ingest ticks journal their RAW wire frames
                # (10 B/row that actually arrived) instead of the
                # re-encoded full-width vector (ISSUE 7)
                if tick_frames is not None:
                    journal.append_tick_frames(journal_base + k, ts,
                                               len(values), tick_frames)
                else:
                    journal.append_tick(journal_base + k, ts, values)
            if chaos is not None:
                # proc_exit fires here — after the row is journaled, so
                # a restart's resume base is unambiguously past it
                chaos.on_tick_ingested(k)
            if auto_release_after:
                # consecutive-silence accounting over THIS tick's values;
                # releases defer to the next tick's membership block (this
                # tick's value vector still matches the current routing)
                nan = np.isnan(values)
                nan_mask = nan if nan.ndim == 1 else \
                    nan.reshape(len(values), -1).all(axis=1)
                for slots, ids, off in routing:
                    for j, sid in enumerate(ids):
                        if nan_mask[off + j]:
                            n = silent_ticks.get(sid, 0) + 1
                            silent_ticks[sid] = n
                            if n >= auto_release_after:
                                release_pending.add(sid)
                        else:
                            silent_ticks.pop(sid, None)
            # held across ticks (micro_chunk) and across collects
            # (depth >= 2): a source reusing a preallocated buffer must not
            # corrupt the emitted values column
            row = (values.copy() if pipeline_depth > 1 or micro_chunk > 1
                   else values, ts)
            for c in range(n_classes):
                chunk_bufs[c].append(row)
                # staggered first flush at c+1 rows tiles class boundaries
                # across ticks; afterwards every class flushes at M rows
                target = micro_chunk if (first_flush_done[c]
                                         or not chunk_stagger) else c + 1
                if len(chunk_bufs[c]) >= target or k + 1 == n_ticks:
                    _flush_class(c)
            if correlator is not None:
                # after this tick's emission: close quiesced windows on
                # the SOURCE clock (ts is the clamped tick timestamp, so
                # a journal replay reproduces every close decision).
                # Alerts lagging in the pipeline carry their own older
                # ts — size --correlate-window above the staleness bound
                # (pipeline_depth * micro_chunk ticks, docs/WORKLOADS.md).
                # The writer offset lets an all-windows-closed tick
                # advance the crash-resume sidecar floor to the sink end.
                correlator.on_tick(ts, tick=k,
                                   sink_offset=writer.sink_offset())
            ticks_run = k + 1
            if learn and checkpoint_every and checkpoint_dir \
                    and (not any(chunk_bufs) or chunk_stagger) \
                    and ticks_run - last_saved >= checkpoint_every \
                    and (lease is None or lease.still_mine()):
                # (the lease gate keeps a paused old leader that woke
                # MID-tick from clobbering the promoted standby's
                # checkpoints before the top-of-tick fence check fires)
                # nothing may be in flight at save time: drain the pipeline
                # first (same rule as replay's drain-before-save). The
                # trigger is due-since-last-save, not a modulus: with
                # micro_chunk > 1 boundaries land only at multiples of M,
                # and `ticks_run % checkpoint_every == 0` would silently
                # degrade the cadence to lcm(M, checkpoint_every)
                if ck_breaker.allow():
                    ck_quarantine_announced = False
                    now = time.perf_counter()
                    ce0 = (phase_s["collect"] + phase_s["emit"]
                           + phase_s["dispatch"])
                    ck0 = phase_s["checkpoint"]
                    _align_boundaries()
                    # drained instant: flush the sink so each meta's
                    # alert cursor equals the on-disk size (exactly-once
                    # resume suppression reads from it)
                    writer.flush_sink()
                    _saved, failed = _save_all(
                        groups, checkpoint_dir, skip=quarantined,
                        chaos=chaos, tick=k,
                        on_failure=lambda gi, e: _on_save_failure(gi, k, e),
                        alerts_offset=writer.sink_offset(),
                        journal_tick=journal_base + ticks_run
                        if journal is not None else None)
                    phase_s["checkpoint"] += (time.perf_counter() - now) - (
                        phase_s["collect"] + phase_s["emit"]
                        + phase_s["dispatch"] - ce0)
                    if trace is not None:
                        trace.add_span("checkpoint", k, now,
                                       max(0.0, phase_s["checkpoint"] - ck0))
                    watchdog.observe_checkpoint(
                        k, phase_s["checkpoint"] - ck0)
                    if failed:
                        # per-group events already emitted; the breaker
                        # decides when a failing disk stops being worth
                        # the drain+fetch cost every round. last_saved is
                        # NOT advanced: the round remains due (retried
                        # next tick until the breaker opens), and the
                        # end-of-run best-effort save must still fire —
                        # advancing it would silently mark failed progress
                        # as saved and suppress both.
                        ck_breaker.record_failure()
                    else:
                        ck_breaker.record_success()
                        checkpoints_saved += 1
                        last_saved = ticks_run
                        if journal is not None and not quarantined:
                            # ticks below every live checkpoint can never
                            # be replayed again — keep the journal
                            # O(checkpoint_every) ticks on disk. With a
                            # group QUARANTINED, compaction pauses: its
                            # restore source is an older checkpoint whose
                            # replay window must stay on disk (a crash-
                            # restart replays it back to health)
                            journal.compact(min(
                                (g.ticks for g in groups), default=0))
                else:
                    # checkpointing quarantined: saves are skipped (and
                    # said so, once per episode) until the breaker's
                    # cooldown admits a probe round. Scoring never
                    # pauses; the round stays due so the probe fires at
                    # the first allowed tick.
                    if not ck_quarantine_announced:
                        ck_quarantine_announced = True
                        _res_event(
                            "checkpoint_quarantined", k,
                            consecutive_failures=
                            ck_breaker.consecutive_failures,
                            cooldown_s=ck_breaker.cooldown_s)
            elapsed = time.perf_counter() - t_start
            latencies[k] = elapsed
            obs_ticks.inc()
            obs_last_tick_wall.set(time.time())
            obs_tick_seconds.observe(elapsed)
            for p in _PHASES:
                obs_phase[p].observe(phase_s[p] - phase_tick0[p])
            if trace is not None:
                trace.add_span("tick", k, t_start, elapsed)
                obs_trace_records.set(trace.total)
                obs_trace_dropped.set(trace.dropped)
            missed_this = watchdog.observe_tick(k, elapsed)
            if missed_this:
                missed += 1
            if degradation is not None:
                # the controller reacts to the deadline verdicts the
                # watchdog just judged; its tick_widen step changes the
                # effective cadence BOTH sides measure against from here on
                _deg_level0 = degradation.level
                degradation.observe(k, missed_this)
                if flight is not None and degradation.level != _deg_level0:
                    # every ladder move (either direction) is a black-box
                    # moment: capture the window that caused it
                    flight.request_dump("degradation_level_change", k)
                new_cadence = cadence_s * degradation.cadence_scale
                if new_cadence != eff_cadence:
                    eff_cadence = new_cadence
                    watchdog.set_cadence(eff_cadence)
            if latency is not None:
                # fold the tick's stage waterfall + lag probes; the SLO
                # evaluation runs after, so any slo_burn dump it queues
                # is flushed by THIS tick's flush_pending below
                latency.record_tick(
                    k, ts, {p: phase_s[p] - phase_tick0[p]
                            for p in _PHASES},
                    elapsed, poll_wall=lat_poll_wall, source=source)
                if slo is not None:
                    slo.on_tick(k)
            if fleet is not None:
                # one guarded int store; the fleet pushes themselves run
                # on the publisher's own thread, never on the tick path
                fleet.note_tick(k)
            if flight is not None:
                flight.record_tick(
                    k, elapsed,
                    {p: phase_s[p] - phase_tick0[p] for p in _PHASES},
                    [a - b for a, b in zip(group_scored, scored_tick0)],
                    missed_this)
                # queued dumps (quarantine/degradation/miss burst) write
                # HERE — after deadline accounting, before the sleep, so
                # the cost never lands inside a phase span; the budget
                # below is recomputed from the wall clock, so a dump
                # consumes this tick's remaining SLEEP, not the cadence
                # (pacing stays honest — the next tick starts on time or
                # immediately, never late-but-unreported)
                flight.flush_pending()
            # a recovery transition can shrink eff_cadence below this
            # tick's elapsed — clamp, don't feed time.sleep a negative.
            # Wall-clock based (not `elapsed`): post-accounting work
            # (bundle dumps above) must shorten the sleep, not stretch
            # the tick period silently past the cadence.
            budget = max(0.0, eff_cadence - (time.perf_counter() - t_start))
            if not missed_this and k + 1 < n_ticks:
                if stop_event is not None:
                    stop_event.wait(budget)  # a shutdown signal ends the sleep
                else:
                    time.sleep(budget)
        for c in range(n_classes):
            if chunk_bufs[c]:
                # early stop mid-chunk: score what was ingested
                _flush_class(c)
        _drain_all()  # every dispatched tick is collected + emitted
    finally:
        if pool is not None:
            pool.shutdown(wait=True)
        if flight is not None:
            # a quarantine raised by the final drain (or an early stop)
            # queued its dump after the last in-loop flush — write it
            flight.flush_pending()
    if learn and checkpoint_dir and not fenced \
            and (lease is None or lease.still_mine()) \
            and (ticks_run > last_saved
                 or journal_replay["replayed_ticks"] > 0):
        # (a FENCED leader skips the final save too: the shared
        # checkpoint dir belongs to the promoted standby now, and a
        # zombie's save would clobber the new timeline's resume state)
        # final state on exit (clean or stopped), like replay_streams — a
        # resume must not lose already-learned ticks. Gated on the dir
        # alone: checkpoint_every=0 with a dir means "save only on exit".
        # Frozen serving (learn=False) never writes: --checkpoint-dir is
        # read-only there (resume the trained model, mutate nothing) — a
        # frozen replica must not clobber the golden checkpoint with
        # advanced tick counters, and two frozen replicas may share a dir.
        # Bypasses the checkpoint breaker (one last best-effort save);
        # failures are evented and counted, never raised over a finished
        # run — each group's previous checkpoint is intact by atomicity.
        writer.flush_sink()
        _saved, failed = _save_all(
            groups, checkpoint_dir, skip=quarantined, chaos=chaos,
            tick=ticks_run,
            on_failure=lambda gi, e: _on_save_failure(gi, ticks_run, e),
            alerts_offset=writer.sink_offset(),
            journal_tick=journal_base + ticks_run
            if journal is not None else None)
        if not failed:
            checkpoints_saved += 1
            if journal is not None and not quarantined:
                # same pause-while-quarantined rule as the in-loop site
                journal.compact(min((g.ticks for g in groups), default=0))
    writer.close()
    lat = {}
    if ticks_run > 0:
        used = latencies[:ticks_run]
        lat = {
            f"latency_p{p}_ms": round(float(np.percentile(used, p)) * 1e3, 3)
            for p in (50, 90, 99)
        }
        lat["latency_max_ms"] = round(float(used.max()) * 1e3, 3)
    extra = {}
    if checkpoint_dir is not None:
        extra["checkpoints_saved"] = checkpoints_saved
        if resumed_from:
            extra["resumed_from"] = resumed_from
            extra["resume_tick_skew"] = resume_tick_skew
    if ticks_run < n_ticks:
        extra["stopped_early"] = True
        extra["ticks_requested"] = n_ticks
    if fenced:
        # the fence story lives in stats + counters, never on the sink
        # (the whole point is that a fenced leader appends NOTHING)
        extra["fenced"] = True
        extra["fenced_line_drops"] = writer.fenced_drops
    if obs_control_degraded is not None:
        extra["control_degraded_ticks"] = control_degraded_ticks
    if ticks_run > 0:
        extra["phase_ms_per_tick"] = {
            k: round(v / ticks_run * 1e3, 2) for k, v in phase_s.items()}
    # resilience accounting (docs/RESILIENCE.md): per-group scored counts
    # are the chaos soak's silent-gap oracle — a group's count must equal
    # its unquarantined tick span exactly, or streams silently stopped
    extra["scored_by_group"] = [int(x) for x in group_scored]
    if quarantined:
        extra["quarantined"] = {
            f"group{gi}": {kk: vv for kk, vv in info.items()
                           if kk != "restore_at"}
            for gi, info in sorted(quarantined.items())}
    if quarantine_log:
        extra["quarantine_log"] = quarantine_log
    if degradation is not None:
        extra["degradation"] = degradation.stats()
    if checkpoint_save_failures:
        extra["checkpoint_save_failures"] = checkpoint_save_failures
    if chaos is not None:
        extra["chaos_injected"] = len(chaos.injected)
    if journal is not None:
        # the durability artifact: what was recovered/replayed, what the
        # torn-tail truncation cost, what exactly-once suppressed
        extra["journal"] = {**journal.stats(), **journal_replay,
                            "suppressed_alerts": writer.suppressed}
    if flight is not None:
        extra["postmortem"] = flight.stats()
    if health is not None:
        # the model-health artifact: scorecard rollup + incident counts
        extra["health"] = health.stats()
    if predictor is not None:
        # the predictive-horizon artifact: divergence rollup, precursor/
        # predicted_incident counts, replay-suppression accounting
        extra["predict"] = predictor.stats()
    if correlator is not None:
        # the correlation artifact: incidents emitted, windows expired,
        # resume re-fold summary (docs/WORKLOADS.md incident schema)
        extra["incidents"] = correlator.stats()
        if correlator_resume is not None:
            extra["incidents"]["resume"] = correlator_resume
    if latency is not None:
        # the detection-latency artifact: per-stage quantiles, the last
        # waterfall, lag gauges (docs/SLO.md triage order starts here)
        extra["latency"] = latency.stats()
    if slo is not None:
        # the SLO verdict the soaks commit: met/bad-frac/budget per
        # declared SLO plus burn-episode counts
        extra["slo"] = slo.verdict()
    if aot_warmup:
        extra["aot_programs_compiled"] = aot_programs
        # cold programs the loop still had to single-flight AFTER the AOT
        # pass — the integration test pins this at zero; nonzero means the
        # knowable-program enumeration missed a shape (a bug, surfaced
        # here instead of as a tail-latency spike)
        extra["cold_compiles_after_warmup"] = max(
            0, len(warmed) - len(prewarmed))
    return {**counter.stats(), "alerts": writer.count, "missed_deadlines": missed,
            "ticks": ticks_run, "cadence_s": cadence_s, "n_groups": len(groups),
            "pipeline_depth": pipeline_depth, "micro_chunk": micro_chunk,
            "chunk_stagger": chunk_stagger,
            "learn": learn,
            **({"auto_registered": auto_registered,
                "auto_rejected": auto_rejected_total} if auto_register else {}),
            **({"auto_released": auto_released} if auto_release_after else {}),
            # effective value: 1 when the pool was never created (single
            # group), so soak reports can't claim threading they didn't get
            "dispatch_threads": eff_threads,
            **extra, **lat, **_occupancy()}


def _save_all(groups, checkpoint_dir: str, skip=(), chaos=None, tick: int = 0,
              on_failure=None, alerts_offset: int | None = None,
              journal_tick: int | None = None) -> tuple[int, int]:
    """One atomic per-group save per group dir (group{i:04d}).

    Quarantined groups (`skip`) are NOT saved: their state may be
    mid-chunk and their last good checkpoint is the restore source.
    Failures are contained per group — reported through `on_failure`,
    never raised — because a full disk must not kill scoring, and
    save_group's temp-sibling atomicity guarantees the previous
    checkpoint is still intact after any failure. Returns
    (saved, failed) counts."""
    from rtap_tpu.service.checkpoint import save_group
    from rtap_tpu.service.shardpath import group_checkpoint_path

    saved = failed = 0
    for gi, grp in enumerate(groups):
        if gi in skip:
            continue
        try:
            if chaos is not None:
                chaos.on_checkpoint_save(gi, tick)
            save_group(grp, group_checkpoint_path(checkpoint_dir, gi),
                       alerts_offset=alerts_offset,
                       journal_tick=journal_tick)
            saved += 1
        except Exception as e:  # noqa: BLE001 — contained per group
            failed += 1
            if on_failure is not None:
                on_failure(gi, e)
    return saved, failed


# rtap: host-boundary — end-of-run stats fetch of two scalar-per-stream
# counters; runs once per serve exit, never on the hot path, and a mesh
# gather of [G] i32 leaves is bytes, not state
def _overflow_total(groups) -> int | None:
    """Sum the per-stream kernel overflow counters (tm_overflow + fwd_of)
    across device groups; None for CPU-oracle groups (the oracle has no
    capacity bounds to overflow)."""
    total = 0
    saw_device = False
    for grp in groups:
        if grp.backend != "tpu":
            continue
        saw_device = True
        st = grp.state
        total += int(np.asarray(st["tm_overflow"]).sum())
        if "fwd_of" in st:
            total += int(np.asarray(st["fwd_of"]).sum())
    return total if saw_device else None


def _occupancy() -> dict:
    """Device HBM occupancy for the throughput stats (observability —
    SURVEY.md §5 metrics/logging). Empty when the backend exposes none
    (CPU test backend). Only consulted when jax is ALREADY in use: a pure
    CPU-oracle run must not initialize the TPU backend as a stats side
    effect (backend init can hang on a wedged tunnel, and would claim the
    exclusive chip out from under a concurrent device run).

    Sums over EVERY local device (the ISSUE 15 device-scope pass caught
    the old ``local_devices()[0]`` read): a sharded fleet's state lives
    spread across the mesh, and reporting one chip's slice as "the" HBM
    figure under-reports by the shard count. Single-device hosts are
    numerically unchanged."""
    import sys

    if "jax" not in sys.modules:
        return {}
    try:
        import jax

        per_device = [d.memory_stats() or {} for d in jax.local_devices()]
        out = {}
        in_use = [s["bytes_in_use"] for s in per_device
                  if "bytes_in_use" in s]
        if in_use:
            out["hbm_bytes_in_use"] = int(sum(in_use))
        peak = [s["peak_bytes_in_use"] for s in per_device
                if "peak_bytes_in_use" in s]
        if peak:
            out["hbm_peak_bytes_in_use"] = int(sum(peak))
        return out
    except Exception:
        return {}
