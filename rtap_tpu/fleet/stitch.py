"""Trace stitching: many per-process Chrome traces, one Perfetto timeline.

Each rtap process exports its own Chrome trace (obs/trace.py) with
timestamps in microseconds since ITS OWN recorder epoch and real
pid/process_name metadata. Stitching rebases every trace onto one fleet
timeline:

- the fleet time origin is the EARLIEST recorder epoch among the input
  traces (``otherData.epoch_unix``), so a leader's final ticks and its
  standby's promotion spans land in causal order on one axis;
- each trace's events shift by ``(epoch_unix - origin) * 1e6`` µs, plus
  that member's registration clock offset when the caller provides the
  aggregator's member roster (the HELLO clock-alignment handshake —
  corrects wall-clock disagreement between hosts, which the per-process
  epochs alone cannot see);
- pids colliding across traces (a restarted process re-using a pid, or
  two hosts) are remapped so every input keeps a distinct Perfetto
  process track, with its ``process_name`` metadata preserved.

``scripts/fleet_trace.py`` is the CLI over this; the function is pure so
the soak harness and tests splice in-process.
"""

from __future__ import annotations

__all__ = ["stitch_traces"]


def stitch_traces(traces: list[dict],
                  members: list[dict] | None = None) -> dict:
    """Splice Chrome trace docs onto one timeline.

    ``traces``: ``chrome_trace()`` outputs (each with ``otherData``
    anchors). ``members``: optional aggregator roster rows
    (``members_view()``) whose ``clock_offset_s`` is applied to the
    matching trace (matched by pid). Returns one Chrome trace doc.
    """
    docs = [t for t in traces if t.get("traceEvents")]
    if not docs:
        return {"traceEvents": [], "otherData": {"stitched_from": 0}}
    offsets_by_pid: dict[int, float] = {}
    for m in members or []:
        if m.get("pid") is not None and m.get("clock_offset_s") is not None:
            offsets_by_pid[int(m["pid"])] = float(m["clock_offset_s"])

    def _epoch(doc: dict) -> float:
        other = doc.get("otherData") or {}
        pid = other.get("pid")
        off = offsets_by_pid.get(int(pid)) if pid is not None else None
        # the member's wall clock, corrected onto the aggregator's:
        # epoch_unix + offset is when this recorder started in FLEET time
        return float(other.get("epoch_unix", 0.0)) + (off or 0.0)

    origin = min(_epoch(d) for d in docs)
    events: list[dict] = []
    used_pids: set[int] = set()
    processes: list[dict] = []
    for doc in docs:
        other = doc.get("otherData") or {}
        pid = int(other.get("pid", 0) or 0)
        shift_us = round((_epoch(doc) - origin) * 1e6, 3)
        out_pid = pid
        while out_pid in used_pids:
            out_pid += 1_000_000  # keep colliding processes distinct
        used_pids.add(out_pid)
        processes.append({
            "pid": pid, "stitched_pid": out_pid,
            "process_name": other.get("process_name"),
            "epoch_unix": other.get("epoch_unix"),
            "shift_us": shift_us,
        })
        for ev in doc["traceEvents"]:
            ev = dict(ev)
            ev["pid"] = out_pid
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + shift_us, 3)
            events.append(ev)
    # metadata events (ph == "M") must precede their process's spans for
    # Perfetto to label tracks; a stable sort on ts keeps them first at
    # equal timestamps because they carry no ts shift of their own
    events.sort(key=lambda e: (0 if e.get("ph") == "M" else 1,
                               float(e.get("ts", 0.0))))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "stitched_from": len(docs),
            "origin_epoch_unix": origin,
            "processes": processes,
        },
    }
