"""rtap_tpu.fleet — the fleet observability plane (ISSUE 19).

One pane of glass over every rtap process. Members (leader, hot
standby, per-shard serves, supervisors) each run a
:class:`FleetPublisher` that pushes full telemetry — registry snapshot,
health rollup, lossless latency sketch states, SLO window counts,
open-incident digest — over an RJ-framed CRC'd record stream
(fleet/protocol.py, the journal/replication framing discipline with a
version-skew-skipping type band). A :class:`FleetAggregator` folds the
pushes into a member table with staleness-driven DOWN marking and an
ordered membership/role event log, and merges: counters sum, gauges
label per member, quantile sketches merge losslessly (fleet p99 is the
p99 of pooled observations, never max-of-member-p99s), and the fleet
SLO verdict is re-derived from pooled window counts against the merged
sketch. :func:`stitch_traces` splices per-process Chrome traces onto
one Perfetto timeline using the registration clock-alignment handshake.

Serve wires this with ``--fleet-join HOST:PORT`` (become a member) and
``--fleet-listen PORT`` (host the aggregator; the ``/fleet/*`` routes
ride the obs HTTP server). docs/FLEET.md is the runbook.

ISSUE 20 adds the CONTROL plane to the same wire band (fleet/control.py):
a :class:`ControlPlane` process owns shard leases / membership / the
shard map behind ``serve --control-listen``, and data planes hold their
fencing epoch through a :class:`ControlLease` (``--control-join``).
"""

from rtap_tpu.fleet.aggregator import (
    FleetAggregator,
    merge_metrics,
    merge_sketches,
    merge_slo,
)
from rtap_tpu.fleet.control import (
    ControlLease,
    ControlPlane,
    control_drain,
    control_read,
    parse_control_addr,
    read_control_journal,
)
from rtap_tpu.fleet.member import FleetPublisher
from rtap_tpu.fleet.protocol import (
    FLEET_BYE,
    FLEET_HELLO,
    FLEET_SNAP,
    FLEET_V,
    FleetWalker,
    pack_fleet,
    unpack_payload,
)
from rtap_tpu.fleet.stitch import stitch_traces

__all__ = [
    "FLEET_BYE",
    "FLEET_HELLO",
    "FLEET_SNAP",
    "FLEET_V",
    "ControlLease",
    "ControlPlane",
    "FleetAggregator",
    "FleetPublisher",
    "FleetWalker",
    "control_drain",
    "control_read",
    "merge_metrics",
    "merge_sketches",
    "merge_slo",
    "pack_fleet",
    "parse_control_addr",
    "read_control_journal",
    "stitch_traces",
    "unpack_payload",
]
