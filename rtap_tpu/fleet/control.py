"""Control plane: one lease per shard, journaled fencing epochs.

ISSUE 20 splits the serve monolith: a small :class:`ControlPlane`
process owns the LEASES (one fencing epoch per shard — the
generalization of the file :class:`~rtap_tpu.resilience.replicate.Lease`
to N shards), the MEMBERSHIP/claims roster, and the SHARD MAP; data
planes join it with ``serve --control-join HOST:PORT`` and talk to it
through :class:`ControlLease`, a drop-in
:class:`~rtap_tpu.resilience.replicate.FencingLease` backend — the tick
loop, alert fence, standby follower and heartbeat thread cannot tell
the two apart.

Durability: every fencing DECISION (grant / release / drain) is
journaled write-ahead through the same RJ record framing as the tick
journal — appended and fsynced BEFORE the grant reply leaves the
socket. A kill-9'd control plane restarts from that journal with every
shard's max granted epoch as the bump floor, so it can never hand out
an epoch <= one it already granted (never re-inverting a fence), and a
restart GRACE window (one lease timeout) refuses takeover grants for a
recovered shard until its surviving holder had a fair chance to
re-heartbeat.

Availability: a data plane whose control plane is unreachable keeps
ticking on its CACHED lease for a bounded, counted window
(``degraded_grace_s``): ``still_mine()`` answers from cache,
``try_acquire`` refuses (a standby never promotes on silence — the
control plane being down is not evidence the leader is), and the loop
counts every degraded tick (``rtap_obs_control_degraded_ticks_total``)
and emits ``control_plane_lost`` / ``control_plane_regained`` events.
Past the window the holder self-fences — fail-safe, never split-brain.

Wire: the control RPCs live in the fleet band (types 35..44, one
short-lived connection per RPC — connect, one request, one reply,
close), so a control stream degrades exactly like a fleet stream: torn
tails wait, garbage resyncs, unknown in-band types skip whole.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque

from rtap_tpu.fleet.protocol import FleetWalker, pack_fleet, unpack_payload
from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry
from rtap_tpu.resilience.replicate import FencingLease

__all__ = ["CTRL_ACQUIRE", "CTRL_DRAIN", "CTRL_GRANT",
           "CTRL_HB", "CTRL_HELLO", "CTRL_JREC", "CTRL_MAP", "CTRL_READ",
           "CTRL_RELEASE", "CTRL_STATE", "ControlLease", "ControlPlane",
           "control_drain", "control_read", "control_rpc",
           "parse_control_addr", "read_control_journal"]

# ---- the control slice of the fleet band (docs/FLEET.md wire table) ----
CTRL_HELLO = 35    # member -> plane: register {member, role, shard, pid}
CTRL_ACQUIRE = 36  # member -> plane: claim a shard lease
CTRL_GRANT = 37    # plane -> member: acquire verdict {ok, epoch, cur}
CTRL_HB = 38       # member -> plane: holder heartbeat {shard,owner,epoch}
CTRL_STATE = 39    # plane -> member: one shard's lease entry + drain flag
CTRL_READ = 40     # member -> plane: read one shard (shard < 0: the map)
CTRL_RELEASE = 41  # member -> plane: orderly handoff (the drain exit)
CTRL_DRAIN = 42    # admin -> plane: mark a shard draining
CTRL_MAP = 43      # plane -> member: full shard map + membership roster
#: journal-only record kind (never leaves the process): one JSON control
#: decision, appended write-ahead
CTRL_JREC = 44

_REQUEST_TYPES = (CTRL_HELLO, CTRL_ACQUIRE, CTRL_HB, CTRL_READ,
                  CTRL_RELEASE, CTRL_DRAIN)
_REPLY_TYPES = (CTRL_GRANT, CTRL_STATE, CTRL_MAP)


def _journal_path(journal_dir: str) -> str:
    return os.path.join(str(journal_dir), "control.journal")


def read_control_journal(journal_dir: str) -> list[dict]:
    """Replay the control journal: every well-framed ``CTRL_JREC``
    payload in append order. The walker discipline makes recovery
    torn-tail tolerant — a record half-written at the kill instant is
    skipped, never mis-parsed (and was never acted on: the reply only
    goes out after fsync)."""
    out: list[dict] = []
    try:
        with open(_journal_path(journal_dir), "rb") as f:
            data = f.read()
    except OSError:
        return out
    walker = FleetWalker(known=(CTRL_JREC,))
    for _typ, payload in walker.feed(data):
        obj = unpack_payload(payload)
        if obj is not None:
            out.append(obj)
    return out


# ------------------------------------------------------------- the plane
class ControlPlane:
    """The lease/membership/shard-map owner (one per deployment).

    State per shard: ``{epoch, owner, ts_mono, timeout_s, meta,
    draining}``. Epoch grants are journaled write-ahead (fsync before
    reply); heartbeats only re-stamp ``ts_mono`` and are NOT journaled —
    a restart recovers epochs exactly and freshness conservatively
    (unknown age + boot grace, see :meth:`_handle_acquire`)."""

    def __init__(self, journal_dir: str, *, port: int = 0,
                 host: str = "127.0.0.1", lease_timeout_s: float = 5.0,
                 registry: TelemetryRegistry | None = None):
        if not journal_dir:
            raise ValueError("control plane needs a journal dir (the "
                             "epoch-durability root)")
        if lease_timeout_s <= 0:
            raise ValueError(
                f"lease_timeout_s must be > 0; got {lease_timeout_s}")
        self.journal_dir = str(journal_dir)
        os.makedirs(self.journal_dir, exist_ok=True)
        self.host, self.port = str(host), int(port)
        self.lease_timeout_s = float(lease_timeout_s)
        self.address: tuple[str, int] | None = None
        self._lock = threading.Lock()
        #: shard -> live lease entry
        self._leases: dict[int, dict] = {}
        #: shard -> max epoch ever journaled (the grant floor; never
        #: regresses, even across release)
        self._granted: dict[int, int] = {}
        #: member name -> last HELLO payload (+ seen timestamp)
        self._members: dict[str, dict] = {}
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        self._accept_thread: threading.Thread | None = None
        self._conn_threads: set = set()
        reg = registry if registry is not None else get_registry()
        self._obs_requests = reg.counter(
            "rtap_obs_control_requests_total",
            "control-plane RPCs served (acquire/heartbeat/read/release/"
            "drain/hello)")
        self._obs_grants = reg.counter(
            "rtap_obs_control_grants_total",
            "shard lease epochs granted (each one journaled write-ahead "
            "before the reply)")
        self.recovered_shards = 0
        self._jf = None
        self._recover()
        #: restart grace anchor: takeover acquires for a recovered shard
        #: whose holder has not re-heartbeat are denied until one full
        #: lease timeout past boot
        self._boot = time.monotonic()

    # ------------------------------------------------------- durability --
    def _recover(self) -> None:
        for rec in read_control_journal(self.journal_dir):
            try:
                shard = int(rec.get("shard", -1))
            except (TypeError, ValueError):
                continue
            if shard < 0:
                continue
            kind = rec.get("kind")
            if kind == "grant":
                try:
                    epoch = int(rec.get("epoch", 0))
                except (TypeError, ValueError):
                    continue
                self._granted[shard] = max(self._granted.get(shard, 0),
                                           epoch)
                self._leases[shard] = {
                    "epoch": epoch, "owner": rec.get("owner"),
                    "ts_mono": None,  # freshness unknown after restart
                    "timeout_s": float(rec.get("timeout_s")
                                       or self.lease_timeout_s),
                    "meta": {}, "draining": False}
            elif kind == "drain":
                entry = self._leases.get(shard)
                if entry is not None:
                    entry["draining"] = True
            elif kind == "release":
                entry = self._leases.get(shard)
                if entry is not None and entry.get("owner") \
                        == rec.get("owner"):
                    entry["owner"] = None
                    entry["ts_mono"] = None
                    entry["draining"] = False
        self.recovered_shards = len(self._granted)
        self._jf = open(_journal_path(self.journal_dir), "ab")

    def _journal(self, kind: str, shard: int, *, epoch: int | None = None,
                 owner: str | None = None,
                 timeout_s: float | None = None) -> None:
        rec: dict = {"kind": kind, "shard": int(shard), "ts": time.time()}
        if epoch is not None:
            rec["epoch"] = int(epoch)
        if owner is not None:
            rec["owner"] = str(owner)
        if timeout_s is not None:
            rec["timeout_s"] = float(timeout_s)
        self._jf.write(pack_fleet(CTRL_JREC, rec))
        self._jf.flush()
        # write-ahead is the whole durability story: the grant the
        # client is about to act on must survive our kill-9, or a
        # restarted plane could re-grant the same epoch and invert
        # the fence
        os.fsync(self._jf.fileno())

    # -------------------------------------------------------- lease math --
    def _entry_stale(self, entry: dict) -> bool:
        if entry.get("owner") is None:
            return True
        ts = entry.get("ts_mono")
        if ts is None:
            return True  # recovered, never re-heartbeat: no freshness
        return time.monotonic() - ts > float(
            entry.get("timeout_s") or self.lease_timeout_s)

    def _view(self, shard: int, entry: dict | None) -> dict | None:
        """The client-facing entry: age measured on OUR monotonic clock
        (members may disagree on wall time), plus a derived wall ``ts``
        so file-lease consumers (stale logs, reports) keep working."""
        if entry is None:
            return None
        ts = entry.get("ts_mono")
        age = (time.monotonic() - ts) if ts is not None else None
        return {"shard": int(shard), "epoch": int(entry["epoch"]),
                "owner": entry.get("owner"), "age_s": age,
                "ts": (time.time() - age) if age is not None else 0.0,
                "draining": bool(entry.get("draining")),
                "meta": dict(entry.get("meta") or {})}

    def _shard_map(self) -> dict:
        shards = {str(s): self._view(s, e)
                  for s, e in sorted(self._leases.items())}
        return {"shards": shards,
                "members": {name: dict(info)
                            for name, info in self._members.items()}}

    # --------------------------------------------------------- handlers --
    def _handle(self, typ: int, p: dict) -> tuple[int, dict]:
        with self._lock:
            self._obs_requests.inc()
            if typ == CTRL_ACQUIRE:
                return self._handle_acquire(p)
            if typ == CTRL_HB:
                return self._handle_hb(p)
            if typ == CTRL_READ:
                shard = int(p.get("shard", 0))
                if shard < 0:
                    return CTRL_MAP, self._shard_map()
                entry = self._leases.get(shard)
                return CTRL_STATE, {
                    "shard": shard, "cur": self._view(shard, entry),
                    "draining": bool(entry and entry.get("draining"))}
            if typ == CTRL_RELEASE:
                return self._handle_release(p)
            if typ == CTRL_DRAIN:
                shard = int(p.get("shard", 0))
                entry = self._leases.get(shard)
                if entry is not None and not entry.get("draining"):
                    self._journal("drain", shard)
                    entry["draining"] = True
                return CTRL_STATE, {
                    "shard": shard, "cur": self._view(shard, entry),
                    "draining": bool(entry and entry.get("draining"))}
            if typ == CTRL_HELLO:
                name = str(p.get("member") or "")
                if name:
                    self._members[name] = {
                        "member": name, "role": p.get("role"),
                        "shard": p.get("shard"), "pid": p.get("pid"),
                        "seen_ts": time.time()}
                return CTRL_MAP, self._shard_map()
            # unreachable: the walker only emits _REQUEST_TYPES
            return CTRL_STATE, {"shard": -1, "cur": None}

    def _handle_acquire(self, p: dict) -> tuple[int, dict]:
        shard = int(p.get("shard", 0))
        owner = str(p.get("owner") or "")
        timeout_s = float(p.get("timeout_s") or self.lease_timeout_s)
        entry = self._leases.get(shard)
        now = time.monotonic()
        if entry is not None and entry.get("owner") == owner \
                and not self._entry_stale(entry):
            # re-acquire by the live holder: same epoch, fresh stamp
            entry["ts_mono"] = now
            entry["timeout_s"] = timeout_s
            if p.get("meta"):
                entry["meta"] = dict(p["meta"])
            return CTRL_GRANT, {"ok": True, "shard": shard,
                                "epoch": int(entry["epoch"]),
                                "cur": self._view(shard, entry)}
        if entry is not None and not self._entry_stale(entry):
            return CTRL_GRANT, {"ok": False, "why": "held",
                                "shard": shard,
                                "cur": self._view(shard, entry)}
        if entry is not None and entry.get("owner") is not None \
                and entry.get("owner") != owner \
                and entry.get("ts_mono") is None \
                and now - self._boot < float(
                    entry.get("timeout_s") or self.lease_timeout_s):
            # restart grace: this shard's holder was granted before our
            # crash and has not re-heartbeat yet — denying the takeover
            # for one lease timeout keeps a control-plane restart from
            # disruptively fencing every healthy leader at once
            return CTRL_GRANT, {"ok": False, "why": "boot_grace",
                                "shard": shard,
                                "cur": self._view(shard, entry)}
        epoch = max(self._granted.get(shard, 0),
                    int(entry["epoch"]) if entry else 0) + 1
        self._journal("grant", shard, epoch=epoch, owner=owner,
                      timeout_s=timeout_s)
        self._granted[shard] = epoch
        self._leases[shard] = {"epoch": epoch, "owner": owner,
                               "ts_mono": now, "timeout_s": timeout_s,
                               "meta": dict(p.get("meta") or {}),
                               "draining": False}
        self._obs_grants.inc()
        return CTRL_GRANT, {"ok": True, "shard": shard, "epoch": epoch,
                            "cur": self._view(shard, self._leases[shard])}

    def _handle_hb(self, p: dict) -> tuple[int, dict]:
        shard = int(p.get("shard", 0))
        owner = str(p.get("owner") or "")
        try:
            epoch = int(p.get("epoch", 0))
        except (TypeError, ValueError):
            epoch = 0
        entry = self._leases.get(shard)
        if entry is not None and entry.get("owner") == owner \
                and int(entry["epoch"]) == epoch:
            # the holder (possibly surviving our restart: ts_mono None
            # re-stamps here, which is what ends its boot-grace limbo)
            entry["ts_mono"] = time.monotonic()
            if p.get("meta"):
                entry["meta"] = dict(p["meta"])
        # any mismatch (epoch advanced, owner changed) just reflects the
        # current entry back — the client's _lost() does the fencing
        return CTRL_STATE, {
            "shard": shard, "cur": self._view(shard, entry),
            "draining": bool(entry and entry.get("draining"))}

    def _handle_release(self, p: dict) -> tuple[int, dict]:
        shard = int(p.get("shard", 0))
        owner = str(p.get("owner") or "")
        entry = self._leases.get(shard)
        if entry is not None and entry.get("owner") == owner:
            self._journal("release", shard, owner=owner)
            entry["owner"] = None
            entry["ts_mono"] = None
            entry["draining"] = False
        return CTRL_STATE, {"shard": shard,
                            "cur": self._view(shard, entry),
                            "draining": False}

    # ------------------------------------------------------------ server --
    def start(self) -> "ControlPlane":
        if self._accept_thread is not None:
            return self
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self.port))
        s.listen(32)
        self._sock = s
        self.address = s.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="rtap-control-accept",
            daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # listener closed: shutdown
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="rtap-control-conn", daemon=True)
            self._conn_threads.add(t)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        walker = FleetWalker(known=_REQUEST_TYPES)
        try:
            conn.settimeout(5.0)
            while not self._stop.is_set():
                try:
                    data = conn.recv(65536)
                except OSError:
                    return
                if not data:
                    return
                for typ, payload in walker.feed(data):
                    p = unpack_payload(payload)
                    if p is None:
                        continue  # future-versioned request: skip whole
                    rtyp, reply = self._handle(typ, p)
                    try:
                        conn.sendall(pack_fleet(rtyp, reply))
                    except OSError:
                        return  # client gone mid-reply: its retry's job
        finally:
            try:
                conn.close()
            except OSError:
                pass  # already torn down by the peer
            self._conn_threads.discard(threading.current_thread())

    def close(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass  # already closed
            self._sock = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
            self._accept_thread = None
        for t in list(self._conn_threads):
            t.join(timeout=1.0)
        if self._jf is not None:
            self._jf.close()
            self._jf = None

    def stats(self) -> dict:
        with self._lock:
            return {"address": list(self.address) if self.address else None,
                    "journal_dir": self.journal_dir,
                    "recovered_shards": self.recovered_shards,
                    "shards": {str(s): self._view(s, e)
                               for s, e in sorted(self._leases.items())},
                    "members": sorted(self._members)}


# -------------------------------------------------------------- one RPC
def control_rpc(addr: tuple[str, int], typ: int, obj: dict, *,
                timeout_s: float = 2.0) -> dict | None:
    """One control RPC: connect, one request, one reply, close. None on
    any transport failure (the caller decides whether that degrades or
    fences — see :class:`ControlLease`)."""
    try:
        with socket.create_connection(
                (str(addr[0]), int(addr[1])), timeout=timeout_s) as s:
            s.settimeout(timeout_s)
            s.sendall(pack_fleet(typ, obj))
            walker = FleetWalker(known=_REPLY_TYPES)
            while True:
                data = s.recv(65536)
                if not data:
                    return None  # peer closed mid-reply
                records = walker.feed(data)
                if records:
                    return unpack_payload(records[0][1])
    except OSError:
        return None


def control_read(addr: tuple[str, int], shard: int = -1, *,
                 timeout_s: float = 2.0) -> dict | None:
    """Read one shard's lease entry (or, with ``shard < 0``, the whole
    shard map + membership roster) — the drill/report probe."""
    return control_rpc(addr, CTRL_READ, {"shard": int(shard)},
                       timeout_s=timeout_s)


def control_drain(addr: tuple[str, int], shard: int, *,
                  timeout_s: float = 2.0) -> dict | None:
    """Mark a shard draining: the holder's next heartbeat reply carries
    the flag, it exits orderly and releases, and its standby takes over
    without waiting out staleness (the rolling-upgrade primitive)."""
    return control_rpc(addr, CTRL_DRAIN, {"shard": int(shard)},
                       timeout_s=timeout_s)


# ------------------------------------------------------------ the lease
class ControlLease(FencingLease):
    """A shard lease held THROUGH the control plane: the drop-in
    :class:`FencingLease` backend for ``serve --control-join``.

    Degradation contract (the tentpole property): every RPC failure
    flips ``degraded`` and queues a ``control_plane_lost`` event;
    while degraded, :meth:`still_mine` keeps answering True from the
    cached grant (the loop keeps ticking, counted per tick),
    :meth:`try_acquire` returns False (a standby NEVER promotes on
    control-plane silence), and :meth:`is_stale` returns False (same
    reason). The window is bounded: unreachable past
    ``degraded_grace_s`` the holder self-fences — an operator gets a
    stalled-alerts page, never a split brain."""

    def __init__(self, addr: tuple[str, int], owner: str, *,
                 shard: int = 0, timeout_s: float = 5.0,
                 meta: dict | None = None,
                 degraded_grace_s: float | None = None,
                 connect_timeout_s: float = 1.0,
                 registry: TelemetryRegistry | None = None):
        super().__init__(owner, timeout_s=timeout_s, meta=meta)
        self.addr = (str(addr[0]), int(addr[1]))
        self.shard = int(shard)
        if self.shard < 0:
            raise ValueError(f"shard must be >= 0; got {shard}")
        #: bounded cached-lease window: unreachable control plane past
        #: this long self-fences the holder (fail-safe beats available)
        self.degraded_grace_s = (float(degraded_grace_s)
                                 if degraded_grace_s is not None
                                 else max(10.0 * self.timeout_s, 30.0))
        if self.degraded_grace_s <= 0:
            raise ValueError(f"degraded_grace_s must be > 0; got "
                             f"{degraded_grace_s}")
        self.connect_timeout_s = float(connect_timeout_s)
        self.draining = False
        #: wired by serve to the loop's stop event: a drain mark becomes
        #: an orderly exit at the next tick boundary
        self.on_drain = None
        self.degraded = False
        self._degraded_since: float | None = None
        self._net_lock = threading.Lock()
        self._cache: dict | None = None
        self.shard_map: dict | None = None
        self._events: deque = deque(maxlen=64)
        reg = registry if registry is not None else get_registry()
        self._obs_rpc_failures = reg.counter(
            "rtap_obs_control_rpc_failures_total",
            "control-plane RPCs that failed in transport (dial refused, "
            "timeout, torn reply); each one extends/starts a degraded "
            "window")
        self._obs_connected = reg.gauge(
            "rtap_obs_control_connected",
            "1 while the last control-plane RPC succeeded, 0 while "
            "degraded (serving on the cached lease)")
        self._obs_connected.set(0)

    # ---------------------------------------------------------- transport --
    def _rpc(self, typ: int, obj: dict) -> dict | None:
        p = control_rpc(self.addr, typ,
                        {"shard": self.shard, **obj},
                        timeout_s=self.connect_timeout_s)
        with self._net_lock:
            if p is None:
                self._obs_rpc_failures.inc()
                self._obs_connected.set(0)
                if not self.degraded:
                    self.degraded = True
                    self._degraded_since = time.monotonic()
                    self._events.append(("control_plane_lost", {
                        "shard": self.shard,
                        "grace_s": self.degraded_grace_s}))
            else:
                self._obs_connected.set(1)
                if self.degraded:
                    outage = time.monotonic() - (self._degraded_since
                                                 or time.monotonic())
                    self.degraded = False
                    self._degraded_since = None
                    self._events.append(("control_plane_regained", {
                        "shard": self.shard,
                        "outage_s": round(outage, 3)}))
        return p

    def pop_events(self) -> list[tuple[str, dict]]:
        """Drain queued lease-lifecycle events (the loop re-emits them
        through ``_res_event`` so they land in counters/trace/alerts)."""
        out: list[tuple[str, dict]] = []
        while True:
            try:
                out.append(self._events.popleft())
            except IndexError:
                return out

    # ------------------------------------------------------ lease surface --
    def read(self) -> dict | None:
        p = self._rpc(CTRL_READ, {})
        if p is None:
            return self._cache  # the bounded-window cache
        cur = p.get("cur")
        self._cache = cur
        return cur

    def _stale(self, cur: dict) -> bool:
        # staleness is judged on the control plane's OWN clock (age_s),
        # never on cross-host wall time; a released or freshness-unknown
        # entry is stale (that is what lets a drained shard's standby
        # promote without waiting out a timeout)
        if cur.get("owner") is None:
            return True
        age = cur.get("age_s")
        if age is None:
            return True
        return float(age) > self.timeout_s

    def is_stale(self) -> bool:
        p = self._rpc(CTRL_READ, {})
        if p is None:
            # an unreachable control plane is NOT evidence the leader is
            # gone — the standby keeps following (no false promotion)
            return False
        cur = p.get("cur")
        self._cache = cur if cur is not None else self._cache
        return cur is None or self._stale(cur)

    def try_acquire(self) -> bool:
        if self.fenced:
            return False
        p = None
        for _attempt in range(3):  # startup race vs the plane's bind
            p = self._rpc(CTRL_ACQUIRE, {
                "owner": self.owner, "timeout_s": self.timeout_s,
                "meta": self.meta})
            if p is not None:
                break
            time.sleep(0.1)
        if p is None or not p.get("ok"):
            if p is not None:
                self._cache = p.get("cur") or self._cache
            return False
        self.epoch = int(p.get("epoch", 0))
        self._cache = p.get("cur")
        self.draining = False
        return True

    def refresh(self) -> bool:
        with self._lock:
            if self.fenced:
                return False
            p = self._rpc(CTRL_HB, {"owner": self.owner,
                                    "epoch": self.epoch,
                                    "meta": self.meta})
            if p is None:
                since = self._degraded_since
                if since is not None and \
                        time.monotonic() - since > self.degraded_grace_s:
                    # the bounded window closed: fail safe. From here
                    # the loop's fence check exits with FENCED_RC.
                    self.fenced = True
                    self._events.append(("control_grace_exhausted", {
                        "shard": self.shard,
                        "grace_s": self.degraded_grace_s}))
                    return False
                # inside the window: keep serving on the cached grant
                self._last_probe = time.monotonic()
                return True
            cur = p.get("cur")
            self._cache = cur
            if self._lost(cur):
                self.fenced = True
                return False
            if (bool(p.get("draining"))
                    or bool((cur or {}).get("draining"))) \
                    and not self.draining:
                self.draining = True
                self._events.append(("shard_draining",
                                     {"shard": self.shard}))
                cb = self.on_drain
                if cb is not None:
                    cb()
            self.refreshes += 1
            self._last_probe = time.monotonic()
            return True

    def still_mine(self) -> bool:
        if self.fenced:
            return False
        if time.monotonic() - self._last_probe < self._probe_interval:
            return True
        # refresh() does the probe bookkeeping (and the degraded-window
        # math) under self._lock — one implementation for the heartbeat
        # thread and the alert fence
        return self.refresh()

    def release(self) -> None:
        """Orderly handoff (the drain exit): give the shard back so the
        standby promotes immediately instead of waiting out staleness.
        Best-effort — an unreachable plane just falls back to the
        staleness path."""
        self._rpc(CTRL_RELEASE, {"owner": self.owner, "epoch": self.epoch})

    def hello(self, role: str) -> dict | None:
        """Register on the membership roster; caches the returned shard
        map snapshot (the claims/topology view the plane owns)."""
        p = self._rpc(CTRL_HELLO, {"member": self.owner, "role": str(role),
                                   "pid": os.getpid()})
        if p is not None:
            self.shard_map = {"shards": p.get("shards") or {},
                              "members": p.get("members") or {}}
        return p

    def holder_meta(self) -> dict:
        cur = self.read() or {}
        # flatten like the file lease (meta keys at top level) so the
        # serve split-brain hint and soak forensics read both the same
        return {**(cur.get("meta") or {}),
                **{k: v for k, v in cur.items() if k != "meta"}}

    def stats(self) -> dict:
        return {"shard": self.shard, "epoch": self.epoch,
                "owner": self.owner, "fenced": self.fenced,
                "degraded": self.degraded, "draining": self.draining,
                "refreshes": self.refreshes,
                "grace_s": self.degraded_grace_s}


def parse_control_addr(spec: str) -> tuple[str, int]:
    """``HOST:PORT`` (empty HOST = 127.0.0.1) -> (host, port). Raises
    ValueError with an operator-facing message on malformed input."""
    host, sep, port_s = str(spec).rpartition(":")
    if not sep:
        raise ValueError(f"control address must be HOST:PORT; got {spec!r}")
    try:
        port = int(port_s)
    except ValueError:
        raise ValueError(
            f"control address port must be an integer; got {port_s!r}")
    if not 0 < port < 65536:
        raise ValueError(f"control address port out of range: {port}")
    return (host or "127.0.0.1", port)
