"""Fleet aggregator: the one-pane-of-glass collector + merge core.

One named thread (``rtap-fleet-agg``) owns a listening socket and a
``selectors`` loop over every member connection: accept, walk the
RJ-framed fleet records (protocol.py), fold HELLO/SNAP/BYE into a
per-member state table, and sweep staleness — a member that misses its
declared ``down_after_s`` of pushes is marked DOWN (and flips back UP on
its next push), with every transition appended to a bounded event log.
That ordered log IS the fleet plane's observed story: failover_soak
asserts "leader DOWN -> standby role_changed to leader at epoch+1"
against the lease-derived truth.

The merge core answers fleet-level questions from member pushes:

- **counters sum** across members (same name+labels = one fleet total);
  **gauges label per member** (a gauge has no cross-process sum — fleet
  drill-down wants "which member", so each row gains a ``member``
  label);
- **latency sketches merge losslessly** (QuantileSketch.from_state +
  merge over identical bucket geometry), so the fleet p99 is THE p99 of
  the pooled observations, never max-of-member-p99s;
- **SLO burn is re-derived from summed window counts** over the merged
  sketch — one fleet verdict for a meshed soak, same clamped
  multi-window thresholds as the per-member tracker.

Reads (the ``/fleet/*`` HTTP routes, the soak harness) take the state
lock briefly and merge on the caller's thread — the collector thread
never blocks on a slow reader.
"""

from __future__ import annotations

import selectors
import socket
import threading
import time
from collections import deque

from rtap_tpu.fleet.protocol import (
    FLEET_BYE,
    FLEET_HELLO,
    FLEET_SNAP,
    FleetWalker,
    unpack_payload,
)
from rtap_tpu.obs.latency import QuantileSketch
from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry

__all__ = ["FleetAggregator", "merge_metrics", "merge_sketches",
           "merge_slo"]


# ------------------------------------------------------------ merge core
def merge_metrics(snaps: dict[str, dict]) -> dict:
    """Merge member registry snapshots: counters sum into fleet totals;
    gauges (and histogram rows) keep per-member identity via an added
    ``member`` label (there is no honest cross-process sum for a gauge
    reading or a bucket layout the members may disagree on)."""
    sums: dict[tuple, dict] = {}
    labeled: list[dict] = []
    for member in sorted(snaps):
        for row in (snaps[member].get("metrics") or {}).get("metrics", []):
            if row.get("type") == "counter":
                key = (row["name"],
                       tuple(sorted((row.get("labels") or {}).items())))
                slot = sums.get(key)
                if slot is None:
                    slot = sums[key] = {
                        "name": row["name"], "type": "counter",
                        **({"labels": dict(row["labels"])}
                           if row.get("labels") else {}),
                        "value": 0, "members": 0}
                slot["value"] += row.get("value", 0)
                slot["members"] += 1
            else:
                labeled.append({
                    **row,
                    "labels": {**(row.get("labels") or {}),
                               "member": member}})
    return {"counters": [sums[k] for k in sorted(sums)],
            "gauges": labeled}


def merge_sketches(states: list[dict]) -> QuantileSketch | None:
    """Rebuild + merge lossless sketch states; None when empty. Raises
    ValueError on geometry mismatch (the caller decides whether to skip
    the member or fail the merge — a fleet quantile silently missing a
    member would be the max-of-p99s lie with extra steps)."""
    merged: QuantileSketch | None = None
    for st in states:
        sk = QuantileSketch.from_state(st)
        merged = sk if merged is None else merged.merge(sk)
    return merged


def _burn(bad: int, total: int, budget: float) -> float:
    return (bad / total / budget) if total else 0.0


def merge_slo(snaps: dict[str, dict]) -> dict:
    """One fleet SLO verdict from summed member window counts + merged
    sketches. Members are pooled per (stage, target, quantile) spec;
    mismatched window lengths are surfaced as conflicts, not pooled
    (a 60-tick and a 600-tick "fast" window do not average)."""
    pooled: dict[tuple, dict] = {}
    conflicts: list[dict] = []
    for member in sorted(snaps):
        for ent in snaps[member].get("slo") or []:
            key = (ent["stage"], ent["target_s"], ent["quantile"])
            slot = pooled.get(key)
            if slot is None:
                slot = pooled[key] = {
                    "stage": ent["stage"], "target_s": ent["target_s"],
                    "quantile": ent["quantile"],
                    "fast_window_ticks": ent["fast_window_ticks"],
                    "slow_window_ticks": ent["slow_window_ticks"],
                    "fast_bad": 0, "fast_total": 0,
                    "slow_bad": 0, "slow_total": 0,
                    "cum_bad": 0, "cum_total": 0,
                    "burn_events": 0, "members": []}
            if (ent["fast_window_ticks"] != slot["fast_window_ticks"]
                    or ent["slow_window_ticks"]
                    != slot["slow_window_ticks"]):
                conflicts.append({"member": member, "stage": ent["stage"],
                                  "why": "window length mismatch"})
                continue
            for k in ("fast_bad", "fast_total", "slow_bad", "slow_total",
                      "cum_bad", "cum_total", "burn_events"):
                slot[k] += ent[k]
            slot["members"].append(member)
    # merged sketches give the fleet observed quantile per stage
    merged_q: dict[str, QuantileSketch] = {}
    sketch_conflicts: list[str] = []
    for member in sorted(snaps):
        sketches = (snaps[member].get("latency") or {}).get("sketches", {})
        for stage, st in sketches.items():
            try:
                sk = QuantileSketch.from_state(st)
                if stage in merged_q:
                    merged_q[stage].merge(sk)
                else:
                    merged_q[stage] = sk
            except (ValueError, KeyError, TypeError):
                sketch_conflicts.append(f"{member}:{stage}")
    slos = []
    for key in sorted(pooled):
        s = pooled[key]
        budget = 1.0 - s["quantile"]
        bad_frac = (s["cum_bad"] / s["cum_total"]) if s["cum_total"] \
            else 0.0
        sk = merged_q.get(s["stage"])
        observed = sk.quantile(s["quantile"], "total") \
            if sk is not None else None
        # the per-member tracker's clamped multi-window thresholds
        # (obs/slo.py on_tick), applied to the POOLED counts
        fast_thr = min(14.0, 0.9 / budget)
        slow_thr = min(6.0, 0.5 / budget)
        fast = _burn(s["fast_bad"], s["fast_total"], budget)
        slow = _burn(s["slow_bad"], s["slow_total"], budget)
        slos.append({
            "slo": f"{s['stage']}@p{round(s['quantile'] * 100, 4):g}",
            "stage": s["stage"],
            "target_s": s["target_s"],
            "quantile": s["quantile"],
            "met": (bad_frac <= budget) if s["cum_total"] else None,
            "samples": s["cum_total"], "bad": s["cum_bad"],
            "bad_frac": round(bad_frac, 6),
            "budget_frac": round(budget, 6),
            "budget_remaining": round(
                1.0 - bad_frac / budget if s["cum_total"] else 1.0, 4),
            "observed_quantile_s": round(observed, 6)
            if observed is not None else None,
            "burn_fast": round(fast, 4), "burn_slow": round(slow, 4),
            "burning": fast >= fast_thr and slow >= slow_thr,
            "burn_events": s["burn_events"],
            "members": s["members"],
        })
    out = {"met": all(v["met"] is not False for v in slos),
           "slos": slos}
    if conflicts:
        out["window_conflicts"] = conflicts
    if sketch_conflicts:
        out["sketch_conflicts"] = sketch_conflicts
    return out


# ------------------------------------------------------------- collector
class _Member:
    __slots__ = ("name", "hello", "snap", "seq", "snapshots", "last_seen",
                 "last_unix", "state", "clock_offset_s", "down_after_s",
                 "left_reason")

    def __init__(self, name: str):
        self.name = name
        self.hello: dict = {}
        self.snap: dict = {}
        self.seq = 0
        self.snapshots = 0
        self.last_seen = time.monotonic()
        self.last_unix = time.time()
        self.state = "up"
        self.clock_offset_s = 0.0
        self.down_after_s = 5.0
        self.left_reason: str | None = None


class _Conn:
    __slots__ = ("sock", "walker", "member")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.walker = FleetWalker()
        self.member: str | None = None


class FleetAggregator:
    """The fleet plane's collector: bind, start(), read merged views.

    ``port=0`` binds an ephemeral localhost port (``.port`` after
    construction — the harness/CLI hands it to members). All public
    ``fleet_*``/``members_view``/``events_view`` readers are
    thread-safe; ``close()`` wakes and joins the collector thread and
    closes every socket deterministically.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1", *,
                 registry: TelemetryRegistry | None = None,
                 default_down_after_s: float = 5.0,
                 sweep_interval_s: float = 0.2,
                 max_events: int = 2048):
        if sweep_interval_s <= 0:
            raise ValueError(
                f"sweep_interval_s must be > 0; got {sweep_interval_s}")
        self.default_down_after_s = float(default_down_after_s)
        #: staleness-check granularity: DOWN detection lags a member's
        #: declared horizon by at most this much (soaks with sub-second
        #: takeover windows tighten it; it is also the idle select
        #: timeout, so don't set it to a busy-poll value)
        self.sweep_interval_s = float(sweep_interval_s)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(64)
        self._lsock.setblocking(False)
        self.host, self.port = self._lsock.getsockname()[:2]
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._lsock, selectors.EVENT_READ, "accept")
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        self._lock = threading.Lock()  # members/events: collector
        self._members: dict[str, _Member] = {}  # writes, route reads
        self._events: deque = deque(maxlen=int(max_events))
        self._conns: dict[int, _Conn] = {}  # collector-thread-only
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        reg = registry if registry is not None else get_registry()
        self._obs_up = reg.gauge(
            "rtap_obs_fleet_members",
            "fleet members by liveness state (push within the member's "
            "declared staleness horizon = up)", state="up")
        self._obs_down = reg.gauge(
            "rtap_obs_fleet_members",
            "fleet members by liveness state (push within the member's "
            "declared staleness horizon = up)", state="down")
        self._obs_snaps = reg.counter(
            "rtap_obs_fleet_snapshots_total",
            "FLEET_SNAP telemetry pushes folded into the fleet state")
        self._obs_skew = reg.counter(
            "rtap_obs_fleet_frames_skipped_total",
            "well-framed fleet records skipped for version skew "
            "(unknown in-band type or future payload version)")
        self._obs_garbage = reg.counter(
            "rtap_obs_fleet_garbage_bytes_total",
            "bytes resynced past on member streams (torn writes, bad "
            "CRC) — the walker recovered at the next record boundary")
        self._obs_downs = reg.counter(
            "rtap_obs_fleet_member_down_total",
            "UP->DOWN staleness transitions observed by the aggregator")

    # ------------------------------------------------------------ events --
    def _event(self, kind: str, member: str, **fields) -> None:
        # lock held by caller
        self._events.append({"t_unix": time.time(), "event": kind,
                             "member": member, **fields})

    def _fold_hello(self, conn: _Conn, p: dict) -> None:
        name = str(p.get("member", ""))
        if not name:
            return
        conn.member = name
        now_unix = time.time()
        with self._lock:
            m = self._members.get(name)
            fresh = m is None
            if fresh:
                m = self._members[name] = _Member(name)
            # supervised-restart lineage (ISSUE 20 satellite): a rejoin
            # whose restarts_total ADVANCED past the previous hello's is
            # the supervisor respawning the same member — an expected
            # recovery, not an operator-page cold return
            prev_restarts = m.hello.get("restarts_total")
            m.hello = p
            m.left_reason = None
            m.last_seen = time.monotonic()
            m.last_unix = now_unix
            m.down_after_s = float(
                p.get("down_after_s", self.default_down_after_s))
            clock = p.get("clock") or {}
            if "unix" in clock:
                # alignment handshake: this member's wall clock vs ours
                # at registration (transit delay rides inside it; good
                # to ~one RTT, plenty for trace splicing)
                m.clock_offset_s = now_unix - float(clock["unix"])
            came_back = m.state != "up"
            m.state = "up"
            extra: dict = {}
            if came_back and not fresh:
                kind = "rejoined"
                restarts = p.get("restarts_total")
                extra["supervised"] = bool(
                    restarts is not None
                    and (prev_restarts is None
                         or int(restarts) > int(prev_restarts)))
                if restarts is not None:
                    extra["restarts_total"] = restarts
                if p.get("last_death_rc") is not None:
                    extra["last_death_rc"] = p.get("last_death_rc")
            else:
                kind = "joined"
            self._event(kind, name,
                        role=p.get("role"), shard=p.get("shard"),
                        lease_epoch=p.get("lease_epoch"),
                        run_epoch=p.get("run_epoch"), pid=p.get("pid"),
                        **extra)

    def _fold_snap(self, conn: _Conn, p: dict) -> None:
        name = str(p.get("member", "")) or conn.member
        if not name:
            return
        self._obs_snaps.inc()
        with self._lock:
            m = self._members.get(name)
            if m is None:
                # HELLO lost to skew: admit the member from its push
                m = self._members[name] = _Member(name)
                m.down_after_s = self.default_down_after_s
                self._event("joined", name, role=p.get("role"),
                            shard=p.get("shard"),
                            lease_epoch=p.get("lease_epoch"),
                            run_epoch=p.get("run_epoch"))
            old_role = m.snap.get("role") or m.hello.get("role")
            old_epoch = m.snap.get("lease_epoch",
                                   m.hello.get("lease_epoch"))
            m.snap = p
            m.seq = int(p.get("seq", m.seq))
            m.snapshots += 1
            m.last_seen = time.monotonic()
            m.last_unix = time.time()
            if m.state != "up":
                m.state = "up"
                self._event("up", name, role=p.get("role"))
            if old_role is not None and p.get("role") != old_role:
                self._event("role_changed", name, role=p.get("role"),
                            old_role=old_role,
                            lease_epoch=p.get("lease_epoch"),
                            old_lease_epoch=old_epoch)

    def _fold_bye(self, conn: _Conn, p: dict) -> None:
        name = str(p.get("member", "")) or conn.member
        if not name:
            return
        with self._lock:
            m = self._members.get(name)
            if m is not None and m.state != "left":
                m.state = "left"
                reason = p.get("reason")
                m.left_reason = str(reason) if reason else None
                if m.left_reason:
                    self._event("left", name, reason=m.left_reason)
                else:
                    self._event("left", name)

    # --------------------------------------------------------- collector --
    def _sweep(self) -> None:
        now = time.monotonic()
        up = down = 0
        with self._lock:
            for m in self._members.values():
                if m.state == "up" and now - m.last_seen > m.down_after_s:
                    m.state = "down"
                    self._obs_downs.inc()
                    self._event(
                        "down", m.name,
                        role=m.snap.get("role") or m.hello.get("role"),
                        last_push_age_s=round(now - m.last_seen, 3))
                if m.state == "up":
                    up += 1
                elif m.state == "down":
                    down += 1
        self._obs_up.set(up)
        self._obs_down.set(down)

    def _drop_conn(self, conn: _Conn) -> None:
        # every conn in _conns is selector-registered (invariant of
        # _service's accept arm) and dropped at most once
        self._sel.unregister(conn.sock)
        self._conns.pop(conn.sock.fileno(), None)
        try:
            conn.sock.close()
        except OSError:
            pass

    def _service(self, key) -> None:
        if key.data == "accept":
            try:
                sock, _addr = self._lsock.accept()
            except OSError:
                return
            sock.setblocking(False)
            conn = _Conn(sock)
            self._conns[sock.fileno()] = conn
            self._sel.register(sock, selectors.EVENT_READ, conn)
            return
        if key.data == "wake":
            try:
                self._wake_r.recv(4096)
            except OSError:
                return  # teardown raced the wake byte; loop re-checks
            return
        conn: _Conn = key.data
        try:
            data = conn.sock.recv(1 << 16)
        except BlockingIOError:
            return
        except OSError:
            self._drop_conn(conn)
            return
        if not data:
            # connection closed without BYE: staleness (not the close)
            # decides DOWN — a member may reconnect within its horizon
            self._drop_conn(conn)
            return
        skew_before = conn.walker.skew_skipped
        garbage_before = conn.walker.garbage_bytes
        for typ, payload in conn.walker.feed(data):
            p = unpack_payload(payload)
            if p is None:
                self._obs_skew.inc()
                continue
            if typ == FLEET_HELLO:
                self._fold_hello(conn, p)
            elif typ == FLEET_SNAP:
                self._fold_snap(conn, p)
            elif typ == FLEET_BYE:
                self._fold_bye(conn, p)
        if conn.walker.skew_skipped > skew_before:
            self._obs_skew.inc(conn.walker.skew_skipped - skew_before)
        if conn.walker.garbage_bytes > garbage_before:
            self._obs_garbage.inc(
                conn.walker.garbage_bytes - garbage_before)

    def _run(self) -> None:
        while not self._stop.is_set():
            for key, _mask in self._sel.select(
                    timeout=self.sweep_interval_s):
                self._service(key)
            self._sweep()
        for conn in list(self._conns.values()):
            self._drop_conn(conn)
        self._sel.close()

    def _close_sockets(self) -> None:
        try:
            self._lsock.close()
        except OSError:
            pass
        try:
            self._wake_r.close()
        except OSError:
            pass
        try:
            self._wake_w.close()
        except OSError:
            pass

    # -------------------------------------------------------- lifecycle --
    def start(self) -> "FleetAggregator":
        self._thread = threading.Thread(
            target=self._run, name="rtap-fleet-agg", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # the wake byte cuts the final select() short; close() is
            # single-owner (the wake pair outlives the collector), so
            # the send cannot race its own close
            self._wake_w.send(b"x")
            self._thread.join(timeout=10.0)
            self._thread = None
        self._sel.close()  # idempotent (the collector closed its own)
        self._close_sockets()

    def wait_members(self, n: int, timeout_s: float = 10.0,
                     state: str = "up") -> bool:
        """Block until >= n members are in ``state`` (harness helper)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if sum(1 for m in self._members.values()
                       if m.state == state) >= n:
                    return True
            time.sleep(0.02)
        return False

    # ------------------------------------------------------------- reads --
    def _snaps(self) -> dict[str, dict]:
        with self._lock:
            return {name: m.snap for name, m in self._members.items()
                    if m.snap}

    def members_view(self) -> list[dict]:
        """Per-member roster: identity, liveness, clock alignment —
        the ``GET /fleet/members`` body and fleet_trace.py's input."""
        now = time.monotonic()
        out = []
        with self._lock:
            for name in sorted(self._members):
                m = self._members[name]
                src = m.snap or m.hello
                out.append({
                    "member": name,
                    "state": m.state,
                    "role": src.get("role"),
                    "shard": src.get("shard"),
                    "pid": m.hello.get("pid"),
                    "run_epoch": src.get("run_epoch"),
                    "lease_epoch": src.get("lease_epoch"),
                    "tick": m.snap.get("tick"),
                    "seq": m.seq,
                    "snapshots": m.snapshots,
                    "last_push_age_s": round(now - m.last_seen, 3),
                    "down_after_s": m.down_after_s,
                    "clock_offset_s": round(m.clock_offset_s, 6),
                    "trace": m.hello.get("trace"),
                    "restarts_total": src.get(
                        "restarts_total", m.hello.get("restarts_total")),
                    "last_death_rc": src.get(
                        "last_death_rc", m.hello.get("last_death_rc")),
                    "left_reason": m.left_reason,
                })
        return out

    def events_view(self) -> list[dict]:
        """The ordered membership/role event log (joined, up, down,
        role_changed, left) — the fleet plane's observed sequence."""
        with self._lock:
            return list(self._events)

    def fleet_metrics(self) -> dict:
        """``GET /fleet/metrics``: counters summed fleet-wide, gauges
        labeled per member, plus the roster."""
        return {"ts": time.time(), **merge_metrics(self._snaps()),
                "members": self.members_view()}

    def fleet_latency(self) -> dict:
        """``GET /fleet/latency``: per-stage quantiles from MERGED
        sketches (pooled counts), plus per-member tick progress."""
        snaps = self._snaps()
        stages: dict[str, QuantileSketch] = {}
        conflicts: list[str] = []
        per_member = {}
        for member in sorted(snaps):
            lat = snaps[member].get("latency") or {}
            per_member[member] = {"ticks": lat.get("ticks", 0),
                                  "detect_samples":
                                      lat.get("detect_samples", 0)}
            for stage, st in (lat.get("sketches") or {}).items():
                try:
                    sk = QuantileSketch.from_state(st)
                    if stage in stages:
                        stages[stage].merge(sk)
                    else:
                        stages[stage] = sk
                except (ValueError, KeyError, TypeError):
                    conflicts.append(f"{member}:{stage}")
        out = {
            "ts": time.time(),
            "stages": {name: {"window": sk.summary("window"),
                              "total": sk.summary("total")}
                       for name, sk in sorted(stages.items())},
            "members": per_member,
        }
        if conflicts:
            out["sketch_conflicts"] = conflicts
        return out

    def fleet_slo(self) -> dict:
        """``GET /fleet/slo``: ONE fleet verdict from pooled window
        counts + merged sketches (never max-of-member-verdicts)."""
        return {"ts": time.time(), **merge_slo(self._snaps())}

    def fleet_health(self) -> dict:
        """``GET /fleet/health``: member health rollups side by side +
        a worst-of fleet verdict (health verdicts don't sum; a fleet is
        as healthy as its sickest member)."""
        snaps = self._snaps()
        per = {}
        worst = "ok"
        groups = 0
        for member in sorted(snaps):
            h = snaps[member].get("health")
            if not h:
                continue
            fleet_block = h.get("fleet", {})
            per[member] = fleet_block
            groups += int(fleet_block.get("groups", 0) or 0)
            if fleet_block.get("verdict") not in (None, "ok"):
                worst = fleet_block.get("verdict")
        return {"ts": time.time(), "verdict": worst if per else None,
                "groups_total": groups, "members": per}

    def fleet_incidents(self) -> dict:
        """``GET /fleet/incidents``: open-window digests per member +
        fleet totals (ROADMAP item 1's cross-shard aggregation rail)."""
        snaps = self._snaps()
        per = {}
        open_total = emitted_total = 0
        for member in sorted(snaps):
            inc = snaps[member].get("incidents")
            if inc is None:
                continue
            per[member] = inc
            open_total += len(inc.get("open_windows") or {})
            emitted_total += int(inc.get("incidents_emitted", 0))
        return {"ts": time.time(), "open_windows_total": open_total,
                "incidents_emitted_total": emitted_total,
                "members": per}

    def member_snaps(self) -> dict[str, dict]:
        """Latest raw FLEET_SNAP per member — the unmerged evidence
        (per-member counters for reconciliation, exact SLO windows)."""
        return self._snaps()

    def snapshot(self) -> dict:
        """Everything at once — the soak-artifact / fleet_report form.
        ``snaps`` carries the raw per-member pushes so the merged views
        stay auditable offline."""
        return {
            "ts": time.time(),
            "members": self.members_view(),
            "events": self.events_view(),
            "metrics": merge_metrics(self._snaps()),
            "latency": self.fleet_latency(),
            "slo": self.fleet_slo(),
            "health": self.fleet_health(),
            "incidents": self.fleet_incidents(),
            "snaps": self._snaps(),
        }
