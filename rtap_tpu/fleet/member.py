"""Fleet member publisher: one thread pushing this process's telemetry.

Every rtap process that joins the fleet plane (``serve --fleet-join
HOST:PORT``, the soak children, a supervisor) runs one
:class:`FleetPublisher`: a single named background thread that dials the
aggregator, introduces itself with a ``FLEET_HELLO`` (identity + clock
anchors), then pushes a full ``FLEET_SNAP`` every ``push_interval_s`` —
registry snapshot, health rollup, lossless latency sketch states, SLO
window counts, open-incident digest. Push is strictly OFF the tick
path: the serve loop at most stores its tick number for the snapshot to
carry (``note_tick``), and a dead/slow aggregator costs the member a
counted failed send per interval, never a blocked tick
(obs/selfbench.measure_fleet gates the snapshot-build cost <= 1% of the
tick budget like every other obs surface).

Role is mutable under a lock (``set_role``): a standby that promotes
mid-connection announces leader/epoch on its next push — the aggregator
sees the promotion as a role change on the SAME member, which is exactly
the sequence failover_soak asserts against the lease-derived truth.
"""

from __future__ import annotations

import os
import socket
import threading
import time

from rtap_tpu.fleet.protocol import (
    FLEET_BYE,
    FLEET_HELLO,
    FLEET_SNAP,
    pack_fleet,
)
from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry

__all__ = ["FleetPublisher"]


class FleetPublisher:
    """Periodic full-telemetry push to a fleet aggregator.

    ``registry``/``health``/``latency``/``slo``/``correlator``/``trace``
    are the process's armed trackers (None = that block is simply absent
    from the push — the aggregator merges what exists, the serve
    flag-gating discipline). ``member`` must be unique fleet-wide (serve
    uses role+pid); duplicate names supersede by latest HELLO.
    """

    def __init__(self, addr: tuple[str, int], member: str, *,
                 role: str = "leader", shard: int = 0,
                 run_epoch: int = 0, lease_epoch: int = 0,
                 push_interval_s: float = 1.0,
                 registry: TelemetryRegistry | None = None,
                 health=None, latency=None, slo=None, correlator=None,
                 trace=None, connect_timeout_s: float = 2.0):
        if push_interval_s <= 0:
            raise ValueError(
                f"push_interval_s must be > 0; got {push_interval_s}")
        self.addr = (str(addr[0]), int(addr[1]))
        self.member = str(member)
        self.push_interval_s = float(push_interval_s)
        #: staleness horizon the member DECLARES at HELLO: miss three
        #: consecutive pushes and the aggregator marks you DOWN
        self.down_after_s = 3.0 * self.push_interval_s
        self.connect_timeout_s = float(connect_timeout_s)
        self.registry = registry if registry is not None else get_registry()
        self.health = health
        self.latency = latency
        self.slo = slo
        self.correlator = correlator
        self.trace = trace
        self._lock = threading.Lock()  # role/epochs/tick: loop thread
        self._role = str(role)         # writes, push thread reads
        self._shard = int(shard)
        self._run_epoch = int(run_epoch)
        self._lease_epoch = int(lease_epoch)
        self._tick = -1
        self._tick_base = 0
        self._seq = 0
        self._bye_reason: str | None = None
        # supervised-restart lineage (ISSUE 20 satellite): the
        # supervisor exports its death accounting into each respawned
        # child's environment; the child's HELLO/SNAP carry it so the
        # aggregator can tell a supervised-restart rejoin from a cold
        # one. Absent env (unsupervised process) = fields omitted.
        try:
            self._restarts_total = int(
                os.environ.get("RTAP_SUPERVISED_RESTARTS", ""))
        except ValueError:
            self._restarts_total = None
        try:
            self._last_death_rc = int(
                os.environ.get("RTAP_SUPERVISED_LAST_RC", ""))
        except ValueError:
            self._last_death_rc = None
        self._sock: socket.socket | None = None  # push-thread-only
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._obs_pushes = self.registry.counter(
            "rtap_obs_fleet_pushes_total",
            "FLEET_SNAP records this member delivered to its aggregator")
        self._obs_push_failures = self.registry.counter(
            "rtap_obs_fleet_push_failures_total",
            "fleet pushes that failed to send (dial refused, peer gone "
            "mid-write); the member reconnects on the next interval")

    # ------------------------------------------------------------ state --
    def set_role(self, role: str, lease_epoch: int | None = None,
                 run_epoch: int | None = None) -> None:
        """Announce a role change (standby promotion) on the next push."""
        with self._lock:
            self._role = str(role)
            if lease_epoch is not None:
                self._lease_epoch = int(lease_epoch)
            if run_epoch is not None:
                self._run_epoch = int(run_epoch)

    def set_tick_base(self, base: int) -> None:
        """Anchor ``note_tick``'s loop-local tick onto the GLOBAL tick
        axis: a resumed or promoted member reports journal-global
        progress, so the fleet's per-member tick column is comparable
        across restarts."""
        with self._lock:
            self._tick_base = int(base)

    def note_tick(self, tick: int) -> None:
        """Record loop progress for the next snapshot (loop thread; one
        guarded int store — the only fleet cost on the tick path)."""
        with self._lock:
            self._tick = self._tick_base + int(tick)

    def attach(self, *, health=None, latency=None, slo=None,
               correlator=None, trace=None) -> None:
        """Wire trackers constructed after the publisher started.

        A standby serve joins the fleet BEFORE its follow loop (so the
        aggregator sees the whole standby phase), but its obs trackers
        only exist after promotion — attach them here; the next push
        carries them. None leaves a tracker unchanged."""
        with self._lock:
            if health is not None:
                self.health = health
            if latency is not None:
                self.latency = latency
            if slo is not None:
                self.slo = slo
            if correlator is not None:
                self.correlator = correlator
            if trace is not None:
                self.trace = trace

    # ------------------------------------------------------------- push --
    def _hello(self) -> dict:
        with self._lock:
            ident = {"role": self._role, "shard": self._shard,
                     "run_epoch": self._run_epoch,
                     "lease_epoch": self._lease_epoch}
            trace = self.trace
        h = {"member": self.member, **ident, "pid": os.getpid(),
             "process_name": f"{self.member}",
             "push_interval_s": self.push_interval_s,
             "down_after_s": self.down_after_s,
             # the clock-alignment handshake: the aggregator pins this
             # member's (wall, perf) pair against its own wall clock so
             # fleet_trace.py can splice trace timelines
             "clock": {"unix": time.time(),
                       "perf": time.perf_counter()}}
        if trace is not None:
            h["trace"] = {"epoch_unix": trace.epoch_unix,
                          "epoch_perf": trace.epoch_perf}
        if self._restarts_total is not None:
            h["restarts_total"] = self._restarts_total
        if self._last_death_rc is not None:
            h["last_death_rc"] = self._last_death_rc
        return h

    def _snap(self) -> dict:
        with self._lock:
            self._seq += 1
            snap = {"member": self.member, "seq": self._seq,
                    "role": self._role, "shard": self._shard,
                    "run_epoch": self._run_epoch,
                    "lease_epoch": self._lease_epoch,
                    "tick": self._tick}
            health, latency = self.health, self.latency
            slo, correlator = self.slo, self.correlator
        snap["t_unix"] = time.time()
        if self._restarts_total is not None:
            snap["restarts_total"] = self._restarts_total
        if self._last_death_rc is not None:
            snap["last_death_rc"] = self._last_death_rc
        snap["metrics"] = self.registry.snapshot()
        if health is not None:
            snap["health"] = health.snapshot()
        if latency is not None:
            snap["latency"] = {
                "ticks": latency.ticks,
                "detect_samples": latency.detect_samples,
                "sketches": latency.sketch_states(),
                "waterfall": latency.last_waterfall,
                "lags": dict(latency.last_lags),
            }
        if slo is not None:
            snap["slo"] = slo.fleet_state()
        if correlator is not None:
            c = correlator.snapshot()
            snap["incidents"] = {
                "open_windows": c.get("open_windows", {}),
                "incidents_emitted": c.get("incidents_emitted", 0),
                "recent": list(c.get("incidents", []))[-5:],
            }
        return snap

    def _send(self, frame: bytes) -> bool:
        """Deliver one frame, dialing if needed; False = counted miss."""
        try:
            if self._sock is None:
                s = socket.create_connection(
                    self.addr, timeout=self.connect_timeout_s)
                s.settimeout(self.connect_timeout_s)
                s.sendall(pack_fleet(FLEET_HELLO, self._hello()))
                self._sock = s
            self._sock.sendall(frame)
            return True
        except OSError:
            self._obs_push_failures.inc()
            self._teardown_sock()
            return False

    def _teardown_sock(self) -> None:
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass  # already torn down by the peer

    def _run(self) -> None:
        # first push immediately: registration must not wait an interval
        # (failover_soak's takeover windows are a few pushes long)
        while True:
            if self._send(pack_fleet(FLEET_SNAP, self._snap())):
                self._obs_pushes.inc()
            if self._stop.wait(self.push_interval_s):
                break
        # final flush: the closing member's last state (completed tick,
        # final counters) must reach the plane before the BYE — merged
        # fleet counters are reconciled against this push
        if self._send(pack_fleet(FLEET_SNAP, self._snap())):
            self._obs_pushes.inc()
        if self._sock is not None:
            bye: dict = {"member": self.member}
            if self._bye_reason:
                # a reasoned departure (drain = rolling upgrade) is an
                # OPERATION, not an outage — the aggregator and
                # fleet_report judge it differently
                bye["reason"] = self._bye_reason
            try:
                self._sock.sendall(pack_fleet(FLEET_BYE, bye))
            except OSError:
                self._obs_push_failures.inc()  # departure is best-effort
        self._teardown_sock()

    # -------------------------------------------------------- lifecycle --
    def start(self) -> "FleetPublisher":
        """Start the push thread (idempotent: a member whose role was
        resolved through the standby path may already be pushing)."""
        if self._thread is None and not self._stop.is_set():
            self._thread = threading.Thread(
                target=self._run, name="rtap-fleet-push", daemon=True)
            self._thread.start()
        return self

    def close(self, reason: str | None = None) -> None:
        """Stop the push thread deterministically (joined, BYE sent).
        ``reason`` rides the BYE payload — ``"drain"`` marks the orderly
        rolling-upgrade departure the exit contracts must not count as
        DOWN."""
        if reason:
            self._bye_reason = str(reason)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
