"""Fleet push protocol: RJ-framed member records (ISSUE 19).

The fleet plane rides the SAME length-framed CRC'd record discipline as
the tick journal and the replication wire (``RJ`` magic, ``<2sBI``
header, crc32 over type+len+payload — rtap_tpu/resilience/journal.py is
the framing's home), with its own type band so a fleet stream can never
be confused with (or corrupted into) a journal/replication stream:

========  ===========  ==================================================
type      name         payload (JSON, versioned)
========  ===========  ==================================================
32        FLEET_HELLO  member identity + clock-alignment anchors, sent
                       once per connection: member name, role
                       (leader/standby/shard-N/supervisor), shard id,
                       run epoch, lease epoch, pid, process_name, the
                       declared push interval, and a
                       ``(time.time, perf_counter)`` clock pair the
                       aggregator uses to align this member's trace
                       timeline with the fleet's.
33        FLEET_SNAP   one full telemetry push: registry snapshot,
                       health rollup, lossless latency sketch states,
                       SLO window counts, open-incident digest, and the
                       member's current role/epochs (promotions surface
                       here without a reconnect).
34        FLEET_BYE    orderly departure (the aggregator marks LEFT
                       instead of waiting out the DOWN staleness).
35..44    CTRL_*       the control-plane slice of the band (ISSUE 20):
                       lease acquire/heartbeat/read/release/drain RPCs,
                       their GRANT/STATE/MAP replies, and the
                       control plane's write-ahead journal record —
                       rtap_tpu/fleet/control.py owns the definitions;
                       the fleet-push walker skips them as skew.
45..47    (reserved)   future fleet records. A well-framed record in
                       this band with a type this build does not know is
                       SKIPPED and counted (``skew_skipped``) — version
                       skew between members and aggregator must degrade
                       to missing fields, never to a desynced stream.
========  ===========  ==================================================

Payloads are JSON objects carrying ``"v": FLEET_V``; a payload whose
``v`` is newer than this build is likewise skipped and counted. Torn
tails wait for more bytes; bad magic / out-of-band type / bad CRC
resyncs to the next magic and counts garbage — the
:class:`FleetWalker` is the replication ``WireWalker`` discipline with
the skew-skipping band added.
"""

from __future__ import annotations

import json
import zlib

from rtap_tpu.resilience.journal import _CRC, _HEADER, _MAGIC, _MAX_PAYLOAD

__all__ = ["FLEET_HELLO", "FLEET_SNAP", "FLEET_BYE", "FLEET_V",
           "FleetWalker", "pack_fleet", "unpack_payload"]

#: fleet payload schema version (bump on incompatible payload changes;
#: readers skip payloads from the future instead of guessing)
FLEET_V = 1

FLEET_HELLO = 32
FLEET_SNAP = 33
FLEET_BYE = 34

#: the whole reserved fleet band: well-framed records here are at worst
#: skipped, never treated as garbage
_FLEET_BAND = range(32, 48)
_KNOWN_TYPES = (FLEET_HELLO, FLEET_SNAP, FLEET_BYE)


def pack_fleet(typ: int, obj: dict) -> bytes:
    """Frame one fleet record: JSON payload in RJ framing. The payload
    always carries the protocol version (writers cannot forget it)."""
    if typ not in _FLEET_BAND:
        raise ValueError(f"type {typ} outside the fleet band "
                         f"[{_FLEET_BAND.start}, {_FLEET_BAND.stop})")
    payload = json.dumps({"v": FLEET_V, **obj},
                         separators=(",", ":")).encode()
    head = _HEADER.pack(_MAGIC, typ, len(payload))
    return head + payload + _CRC.pack(zlib.crc32(head[2:] + payload))


def unpack_payload(payload: bytes) -> dict | None:
    """Decode one record's JSON payload; None for undecodable or
    future-versioned payloads (the caller counts the skip)."""
    try:
        obj = json.loads(payload)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(obj, dict) or int(obj.get("v", 0)) > FLEET_V:
        return None
    return obj


class FleetWalker:
    """Incremental fleet-record stream walker: feed() recv chunks, get
    ``(typ, payload_bytes)`` records out. Torn tails wait; bad
    magic/CRC/out-of-band type resyncs to the next magic (counted in
    ``garbage_bytes``/``bad_crc``); well-framed in-band records of an
    unknown type are dropped whole and counted in ``skew_skipped``.

    ``known`` selects which in-band types this consumer emits (default:
    the fleet push records) — the control plane (fleet/control.py) rides
    the same walker over its own slice of the band, so both streams
    share one degradation discipline."""

    def __init__(self, known: tuple = _KNOWN_TYPES):
        self._known = tuple(known)
        self._buf = bytearray()
        self.records = 0
        self.garbage_bytes = 0
        self.bad_crc = 0
        self.skew_skipped = 0

    def feed(self, data: bytes) -> list[tuple[int, bytes]]:
        self._buf += data
        buf = bytes(self._buf)
        n = len(buf)
        out: list[tuple[int, bytes]] = []
        off = 0
        while off + _HEADER.size + _CRC.size <= n:
            magic, typ, ln = _HEADER.unpack_from(buf, off)
            if magic != _MAGIC or typ not in _FLEET_BAND \
                    or ln > _MAX_PAYLOAD:
                nxt = buf.find(_MAGIC, off + 1)
                skip_to = nxt if nxt != -1 else max(off + 1, n - 1)
                self.garbage_bytes += skip_to - off
                off = skip_to
                continue
            end = off + _HEADER.size + ln + _CRC.size
            if end > n:
                break  # torn tail: wait for more bytes
            payload = buf[off + _HEADER.size:end - _CRC.size]
            (crc,) = _CRC.unpack_from(buf, end - _CRC.size)
            if crc != zlib.crc32(buf[off + 2:off + _HEADER.size] + payload):
                self.bad_crc += 1
                nxt = buf.find(_MAGIC, off + 1)
                skip_to = nxt if nxt != -1 else max(off + 1, n - 1)
                self.garbage_bytes += skip_to - off
                off = skip_to
                continue
            if typ not in self._known:
                # CRC held: a future record, not corruption — skip WHOLE
                self.skew_skipped += 1
                off = end
                continue
            out.append((typ, payload))
            off = end
        del self._buf[:off]
        self.records += len(out)
        return out
