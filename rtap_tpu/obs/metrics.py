"""Telemetry primitives: Counter / Gauge / Histogram + TelemetryRegistry.

The serve stack's self-measurement seam (SURVEY.md §5 "Metrics / logging").
Every host-side hot path — the tick loop's phases, alert emission, ingest
health, checkpoint saves — emits through ONE process-wide registry instead
of ad-hoc ``perf_counter()`` dicts and stdout lines, and the exposition
layer (obs/expo.py) renders the same registry as Prometheus v0 text or a
JSONL snapshot.

Design constraints (the tick loop scores 100k+ streams at 1 s cadence and
its instrumentation budget is <= 1% of the tick — bench.py --obs-bench and
tests/unit/test_obs.py pin it):

- **Lock-free writer fast path.** No instrument takes a lock on ``inc`` /
  ``set`` / ``observe``. Instead every writer thread owns a private cell
  (keyed by ``threading.get_ident()``), so concurrent writers never
  read-modify-write shared state — the same sharding trick as Prometheus
  multiprocess mode, per thread instead of per process. Readers sum the
  cells; a snapshot that races a brand-new writer thread's first write
  retries (the only cross-thread interaction, and it is read-only).
- **Allocation-free histogram observe.** Buckets are a numpy int64 array
  per writer thread, bucket search is ``bisect`` over a plain-float edge
  list: O(log n_buckets), no numpy scalar boxing, no per-observe
  allocation after a thread's first observe.
- **Fixed log-spaced buckets** suited to the 1 ms – 10 s tick-latency range
  (:func:`log_buckets`): sparse-distributed-representation serving is
  dominated by tail behavior (warm-up compiles caused the 9/3600 missed
  ticks in the 1-hour soak), so the measurement primitive is a histogram,
  never an average.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from typing import Sequence

import numpy as np

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "TelemetryRegistry",
    "get_registry",
    "log_buckets",
]

_VALID_TYPES = ("counter", "gauge", "histogram")


def log_buckets(lo: float = 1e-3, hi: float = 10.0,
                per_decade: int = 5) -> tuple[float, ...]:
    """Log-spaced histogram bucket upper bounds covering [lo, hi].

    Defaults span 1 ms .. 10 s at 5 buckets/decade — the tick-latency range
    the 1 s-cadence serve path lives in (sub-ms phases up through the
    multi-second warm-up-compile outliers the soak forensics chase).
    """
    if not (0 < lo < hi):
        raise ValueError(f"need 0 < lo < hi; got lo={lo}, hi={hi}")
    if per_decade < 1:
        raise ValueError(f"per_decade must be >= 1; got {per_decade}")
    n = int(round(np.log10(hi / lo) * per_decade))
    edges = lo * (10.0 ** (np.arange(n + 1) / per_decade))
    # float roundoff must not drop the intended top edge
    edges[-1] = max(edges[-1], hi)
    return tuple(float(e) for e in edges)


def _sum_cells(cells: dict) -> float:
    """Sum a per-thread cell dict, tolerating a concurrent first write from
    a brand-new thread (dict resize mid-iteration raises RuntimeError —
    vanishingly rare; retry, then fall back to a point-in-time copy)."""
    for _ in range(8):
        try:
            return sum(cells.values())
        except RuntimeError:
            continue
    return sum(dict(cells).values())


class _Instrument:
    """Common identity: name + fixed label set (one instrument per child)."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: dict[str, str] | None = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})

    def _meta(self) -> dict:
        d: dict = {"name": self.name, "type": self.kind}
        if self.labels:
            d["labels"] = dict(self.labels)
        return d


class Counter(_Instrument):
    """Monotonic counter. ``inc`` touches only the calling thread's cell —
    lock-free and safe under concurrent writers (each thread owns its key)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: dict[str, str] | None = None):
        super().__init__(name, help, labels)
        self._cells: dict[int, float] = {}

    def inc(self, n: float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease (n={n})")
        cells = self._cells
        tid = threading.get_ident()
        cells[tid] = cells.get(tid, 0.0) + n

    @property
    def value(self) -> float:
        return _sum_cells(self._cells)

    def snapshot_value(self):
        return self.value

    def reset(self) -> None:
        self._cells.clear()


class Gauge(_Instrument):
    """Last-write-wins point-in-time value. ``set`` is a single attribute
    store (atomic under the GIL); ``inc``/``dec`` are single-writer
    conveniences (document ownership if you share one across threads)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: dict[str, str] | None = None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, n: float = 1) -> None:
        self._value += n

    def dec(self, n: float = 1) -> None:
        self._value -= n

    @property
    def value(self) -> float:
        return self._value

    def snapshot_value(self):
        return self._value

    def reset(self) -> None:
        self._value = 0.0


class _HistShard:
    """One writer thread's private histogram state (no cross-thread writes)."""

    __slots__ = ("counts", "sum", "min", "max")

    def __init__(self, n_buckets: int):
        self.counts = np.zeros(n_buckets, np.int64)
        self.sum = 0.0
        self.min = np.inf
        self.max = -np.inf


class Histogram(_Instrument):
    """Fixed-bucket histogram with Prometheus ``le`` (v <= edge) semantics.

    ``observe`` is O(log n_buckets) and allocation-free on a thread's
    second and later observes: bisect over a plain-float edge list, then an
    in-place numpy int64 bucket increment in the calling thread's shard.
    The implicit +Inf bucket is the last slot.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] | None = None,
                 labels: dict[str, str] | None = None):
        super().__init__(name, help, labels)
        edges = tuple(float(e) for e in (buckets or log_buckets()))
        if not edges or any(b <= a for a, b in zip(edges, edges[1:])):
            raise ValueError(
                f"histogram {name} buckets must be strictly increasing and "
                f"non-empty; got {edges}")
        self.edges = edges
        self._edges_list = list(edges)  # bisect target (no numpy boxing)
        self._shards: dict[int, _HistShard] = {}

    def observe(self, v: float) -> None:
        shard = self._shards.get(threading.get_ident())
        if shard is None:
            shard = self._shards.setdefault(
                threading.get_ident(), _HistShard(len(self.edges) + 1))
        shard.counts[bisect_left(self._edges_list, v)] += 1
        shard.sum += v
        if v < shard.min:
            shard.min = v
        if v > shard.max:
            shard.max = v

    def _merged(self) -> _HistShard:
        out = _HistShard(len(self.edges) + 1)
        for _ in range(8):
            try:
                shards = list(self._shards.values())
                break
            except RuntimeError:
                continue
        else:
            shards = list(dict(self._shards).values())
        for s in shards:
            out.counts += s.counts
            out.sum += s.sum
            out.min = min(out.min, s.min)
            out.max = max(out.max, s.max)
        return out

    @property
    def count(self) -> int:
        return int(self._merged().counts.sum())

    @property
    def sum(self) -> float:
        return self._merged().sum

    def snapshot_value(self) -> dict:
        m = self._merged()
        count = int(m.counts.sum())
        cum = np.cumsum(m.counts)
        out = {
            "buckets": {repr(e): int(c) for e, c in zip(self.edges, cum)},
            "count": count,
            "sum": m.sum,
        }
        out["buckets"]["+Inf"] = count
        if count:
            out["min"] = m.min
            out["max"] = m.max
        return out

    def reset(self) -> None:
        self._shards.clear()


def _key(name: str, labels: dict[str, str]) -> tuple:
    return (name, tuple(sorted(labels.items())))


class TelemetryRegistry:
    """Process-wide instrument registry: get-or-create by (name, labels).

    Creation takes a lock (cold path, once per instrument); the returned
    instruments are cached by every call site, so steady-state emission
    never touches the registry. One metric NAME has one type and one help
    string — a type conflict is a programming error and raises.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: dict[tuple, _Instrument] = {}
        self._types: dict[str, str] = {}
        self._help: dict[str, str] = {}
        self._buckets: dict[str, tuple] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: dict[str, str], **kw) -> _Instrument:
        key = _key(name, labels)
        inst = self._instruments.get(key)
        if inst is not None:
            if inst.kind != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}")
            return inst
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                prior = self._types.get(name)
                if prior is not None and prior != cls.kind:
                    raise ValueError(
                        f"metric {name!r} already registered as {prior}, "
                        f"requested {cls.kind}")
                if cls.kind == "histogram":
                    buckets = tuple(kw.get("buckets") or log_buckets())
                    prior_b = self._buckets.setdefault(name, buckets)
                    if prior_b != buckets:
                        raise ValueError(
                            f"histogram {name!r} already registered with "
                            f"buckets {prior_b}; one family, one grid")
                    kw["buckets"] = buckets
                inst = cls(name, help=help, labels=labels, **kw)
                self._types[name] = cls.kind
                if help:
                    self._help.setdefault(name, help)
                self._instruments[key] = inst
            elif inst.kind != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"requested {cls.kind}")
        return inst

    def counter(self, name: str, help: str = "", **labels: str) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", **labels: str) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] | None = None,
                  **labels: str) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def collect(self) -> list[_Instrument]:
        """Stable-ordered instrument list (by name, then label items)."""
        with self._lock:
            items = sorted(self._instruments.items())
        return [inst for _, inst in items]

    def help_for(self, name: str) -> str:
        return self._help.get(name, "")

    def snapshot(self) -> dict:
        """Point-in-time JSON-able view of every instrument: the JSONL
        export unit (obs/expo.py) and the no-network hw-session surface."""
        return {
            "ts": time.time(),
            "metrics": [
                {**inst._meta(), "value": inst.snapshot_value()}
                for inst in self.collect()
            ],
        }

    def reset(self) -> None:
        """Zero every instrument (tests / between measurement sections).
        Instruments stay registered — cached references remain valid."""
        for inst in self.collect():
            inst.reset()


_REGISTRY = TelemetryRegistry()


def get_registry() -> TelemetryRegistry:
    """The process-wide default registry every serve-path instrument lands
    in. Library code takes an optional registry and defaults to this."""
    return _REGISTRY
