"""Host-side model-health tracking: scorecards, drift, incidents (ISSUE 6).

The fused step's on-device reducers (ops/health_tpu.py) hand the loop one
small aggregate leaf per (group, tick). This module folds those into:

- **Per-group scorecards** — segment-pool occupancy (fraction +
  histogram), synapse-pool fill and permanence sketch, active-column /
  predictive-cell sparsity, predicted->active hit rate, and streaming
  anomaly-score quantiles from an EWMA'd score histogram.
- **EWMA drift detection** on the score distribution: a fast and a slow
  exponentially-weighted histogram per group; their total-variation
  distance is the drift metric. A detector whose score distribution
  walks away from its own baseline is degrading even when every tick
  hits its deadline.
- **Health-state events** on the incident stream (same contract as the
  watchdog/resilience events): ``pool_saturated``,
  ``sparsity_collapsed``, ``score_drift`` — edge-triggered with
  hysteresis, each also requesting a flight-recorder postmortem dump
  (a health incident is a black-box moment like a quarantine).
- **Registry gauges** (fleet rollups — they ride the normal snapshot
  file, so hw-session soaks get health numbers for free) and the
  ``GET /health`` JSON body (obs/expo.py).

Thread model: :meth:`fold` is called from the serve loop thread only
(emission is single-threaded by contract); :meth:`snapshot` may be
called concurrently by the obs HTTP server — like ``/trace``, the read
is point-in-time diagnostic data, not a consistent cut.

Also here: :func:`bump_run_epoch` — the restart-continuity counter
(ISSUE 6 satellite). A supervised serve child resets every in-process
counter when it restarts; the run epoch is persisted beside the
incident stream and bumped once per process start, so dashboards can
tell a restart reset from a counter rollover via the
``rtap_obs_run_epoch`` gauge.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry
from rtap_tpu.ops.health_tpu import OCC_BINS, PERM_BINS, SCORE_BINS

__all__ = ["HealthTracker", "bump_run_epoch", "set_build_info"]

#: health-state event vocabulary (docs/TELEMETRY.md, docs/POSTMORTEM.md)
HEALTH_EVENTS = ("pool_saturated", "sparsity_collapsed", "score_drift")


class _GroupHealth:
    """One group's folded health state (bounded: a few fixed vectors)."""

    __slots__ = ("ticks", "ticks_scored", "last", "hit_num", "hit_den",
                 "fast", "slow", "drift_tvd", "drifting", "saturated",
                 "collapsed", "last_tick")

    def __init__(self):
        self.ticks = 0          # health leaves folded
        self.ticks_scored = 0   # leaves with at least one scored stream
        self.last: dict = {}    # latest per-tick scalar/vector values
        self.hit_num = 0.0      # cumulative predicted->active numerator
        self.hit_den = 0.0
        self.fast = np.zeros(SCORE_BINS, np.float64)  # EWMA'd score dist
        self.slow = np.zeros(SCORE_BINS, np.float64)  # the baseline
        self.drift_tvd = 0.0
        self.drifting = False
        self.saturated = False
        self.collapsed = False
        self.last_tick = -1


class HealthTracker:
    """Folds per-(group, tick) health leaves into fleet scorecards.

    Construction registers the fleet gauges once; :meth:`fold` is the
    only hot-path call (one per collected chunk per group — a few
    numpy ops over ~40-element vectors, self-benchmarked by
    ``obs/selfbench.measure_health`` and gated <= 1% of the tick budget
    by ``bench.py --obs-bench``).

    `sink` (callable taking one JSON-able event dict) and `flight`
    (obs.FlightRecorder) may be attached after construction —
    ``live_loop`` wires the alert-stream writer and the flight recorder
    in, exactly like the watchdog and the degradation controller.
    """

    def __init__(self, cfg, registry: TelemetryRegistry | None = None,
                 sink=None, flight=None,
                 occupancy_threshold: float = 0.9,
                 sparsity_min_frac: float = 0.5,
                 drift_threshold: float = 0.25,
                 drift_min_ticks: int = 120,
                 alpha_fast: float = 0.1, alpha_slow: float = 0.01,
                 warmup_ticks: int = 16):
        if not (0.0 < occupancy_threshold <= 1.0):
            raise ValueError(
                f"occupancy_threshold must be in (0, 1]; got "
                f"{occupancy_threshold}")
        if not (0.0 <= sparsity_min_frac < 1.0):
            raise ValueError(
                f"sparsity_min_frac must be in [0, 1); got "
                f"{sparsity_min_frac}")
        if not (0.0 < drift_threshold <= 1.0):
            raise ValueError(
                f"drift_threshold must be in (0, 1]; got {drift_threshold}")
        if drift_min_ticks < 1:
            raise ValueError(
                f"drift_min_ticks must be >= 1; got {drift_min_ticks}")
        if not (0.0 < alpha_slow <= alpha_fast <= 1.0):
            raise ValueError(
                "need 0 < alpha_slow <= alpha_fast <= 1; got "
                f"{alpha_slow}, {alpha_fast}")
        self.cfg = cfg
        # the healthy active-column fraction: inhibition selects exactly
        # k winners whenever input drives any column past the stimulus
        # threshold, so a LIVE stream far below k/C has a starved SP —
        # the sparsity-collapse signal (SDR theory: sparsity carries the
        # representation; a collapsed SDR can't discriminate patterns)
        self.expected_active_frac = (
            cfg.sp.num_active_columns / cfg.sp.columns)
        self.occupancy_threshold = float(occupancy_threshold)
        self.sparsity_min_frac = float(sparsity_min_frac)
        self.drift_threshold = float(drift_threshold)
        self.drift_min_ticks = int(drift_min_ticks)
        self.alpha_fast = float(alpha_fast)
        self.alpha_slow = float(alpha_slow)
        self.warmup_ticks = int(warmup_ticks)
        self.sink = sink
        self.flight = flight
        self._groups: dict[int, _GroupHealth] = {}
        self.events_total = 0
        self._events_by_kind: dict[str, int] = {}
        reg = registry or get_registry()
        self._obs_events = {
            kind: reg.counter(
                "rtap_obs_health_events_total",
                "model-health incidents by kind (pool_saturated / "
                "sparsity_collapsed / score_drift)", event=kind)
            for kind in HEALTH_EVENTS
        }
        self._obs_occ = reg.gauge(
            "rtap_obs_health_pool_occupancy_max",
            "worst per-group mean segment-pool occupancy fraction "
            "(ROADMAP-3 right-sizing signal)")
        self._obs_hit = reg.gauge(
            "rtap_obs_health_hit_rate",
            "fleet predicted->active column hit rate (cumulative "
            "mean; 1 - raw anomaly score weighted by active columns)")
        self._obs_sparsity = reg.gauge(
            "rtap_obs_health_active_col_frac",
            "fleet mean active-column fraction at the latest folded tick")
        self._obs_drift = reg.gauge(
            "rtap_obs_health_score_drift_max",
            "worst per-group score-distribution drift (total-variation "
            "distance between the fast and slow EWMA histograms)")
        self._obs_drifting = reg.gauge(
            "rtap_obs_health_groups_drifting",
            "groups currently past the score-drift threshold")
        self._obs_fold_seconds = reg.histogram(
            "rtap_obs_health_fold_seconds",
            "wall seconds per HealthTracker.fold call (one per collected "
            "chunk per group; gated <= 1% of the tick budget by "
            "bench.py --obs-bench)")

    # ------------------------------------------------------------ fold --
    def fold(self, group: int, leaves: dict, tick: int = -1) -> None:
        """Fold one collected chunk's health leaves ([T, ...] arrays from
        ``StreamGroup.last_health``) into group `group`'s scorecard and
        evaluate the health-state conditions once per call."""
        t0 = time.perf_counter()
        g = self._groups.get(group)
        if g is None:
            g = self._groups[group] = _GroupHealth()
        scored = np.atleast_1d(np.asarray(leaves["scored"]))
        hists = np.atleast_2d(np.asarray(leaves["score_hist"], np.float64))
        hit_num = np.atleast_1d(np.asarray(leaves["hit_num"], np.float64))
        hit_den = np.atleast_1d(np.asarray(leaves["hit_den"], np.float64))
        af, asl = self.alpha_fast, self.alpha_slow
        for i in range(len(scored)):
            g.ticks += 1
            n = float(scored[i])
            if n > 0:
                p = hists[i] / n
                if g.ticks_scored == 0:
                    g.fast[:] = p
                    g.slow[:] = p
                else:
                    g.fast += af * (p - g.fast)
                    g.slow += asl * (p - g.slow)
                g.ticks_scored += 1
        g.hit_num += float(hit_num.sum())
        g.hit_den += float(hit_den.sum())
        # scorecard state + condition checks track the latest tick that
        # actually SCORED live streams: an all-NaN outage tick reduces
        # every live-masked mean to 0, and adopting those zeros would
        # both report false health (occupancy "dropping" to 0 during a
        # source outage) and reset the saturation edge-trigger so the
        # incident re-fires on every source recovery (flap storm)
        live_idx = np.nonzero(scored > 0)[0]
        g.last_tick = int(tick)
        if live_idx.size:
            i = int(live_idx[-1])
            g.last = {
                "occ_hist": [int(x)
                             for x in np.asarray(leaves["occ_hist"])[i]],
                "seg_occ_frac": float(
                    np.asarray(leaves["seg_occ_frac"])[i]),
                "syn_frac": float(np.asarray(leaves["syn_frac"])[i]),
                "perm_hist": [round(float(x), 6)
                              for x in np.asarray(leaves["perm_hist"])[i]],
                "perm_conn_frac": float(
                    np.asarray(leaves["perm_conn_frac"])[i]),
                "act_col_frac": float(
                    np.asarray(leaves["act_col_frac"])[i]),
                "pred_cell_frac": float(
                    np.asarray(leaves["pred_cell_frac"])[i]),
                "scored": int(scored[i]),
            }
            self._evaluate(group, g, tick)
        self._set_fleet_gauges()
        self._obs_fold_seconds.observe(time.perf_counter() - t0)

    # ------------------------------------------------- incident logic --
    def _event(self, kind: str, tick: int, group: int, **fields) -> None:
        self.events_total += 1
        self._events_by_kind[kind] = self._events_by_kind.get(kind, 0) + 1
        self._obs_events[kind].inc()
        ev = {"event": kind, "tick": int(tick), "group": int(group),
              **fields}
        if self.flight is not None:
            # a health incident is a black-box moment like a quarantine:
            # capture the window that led here (queued; the loop writes
            # it after deadline accounting, throttled per reason)
            self.flight.record_event(ev)
            self.flight.request_dump(kind, tick)
        if self.sink is not None:
            self.sink(ev)

    def _evaluate(self, gi: int, g: _GroupHealth, tick: int) -> None:
        """Edge-triggered conditions with hysteresis: each fires once on
        entry and re-arms only after the metric clears a margin below its
        threshold (a value oscillating at the line must not storm the
        incident stream)."""
        occ = g.last.get("seg_occ_frac", 0.0)
        if not g.saturated and occ >= self.occupancy_threshold:
            g.saturated = True
            self._event("pool_saturated", tick, gi, occupancy=round(occ, 4),
                        threshold=self.occupancy_threshold,
                        occ_hist=g.last.get("occ_hist"))
        elif g.saturated and occ < 0.9 * self.occupancy_threshold:
            g.saturated = False
        act = g.last.get("act_col_frac", 0.0)
        floor = self.sparsity_min_frac * self.expected_active_frac
        # only judged on ticks that scored live streams, past the model's
        # bring-up window (an empty fleet or tick 0 has nothing to say)
        if g.last.get("scored", 0) > 0 and g.ticks >= self.warmup_ticks:
            if not g.collapsed and act < floor:
                g.collapsed = True
                self._event(
                    "sparsity_collapsed", tick, gi,
                    active_col_frac=round(act, 5),
                    expected_frac=round(self.expected_active_frac, 5),
                    floor=round(floor, 5))
            elif g.collapsed and act >= min(
                    1.25 * floor, self.expected_active_frac):
                g.collapsed = False
        tvd = 0.0
        if g.ticks_scored >= self.drift_min_ticks:
            tvd = 0.5 * float(np.abs(g.fast - g.slow).sum())
        g.drift_tvd = tvd
        if not g.drifting and tvd >= self.drift_threshold:
            g.drifting = True
            self._event("score_drift", tick, gi, tvd=round(tvd, 4),
                        threshold=self.drift_threshold,
                        quantiles=self._quantiles(g.fast),
                        baseline_quantiles=self._quantiles(g.slow))
        elif g.drifting and tvd < 0.5 * self.drift_threshold:
            g.drifting = False

    def _set_fleet_gauges(self) -> None:
        gs = list(self._groups.values())
        if not gs:
            return
        self._obs_occ.set(max(
            (g.last.get("seg_occ_frac", 0.0) for g in gs), default=0.0))
        den = sum(g.hit_den for g in gs)
        self._obs_hit.set(sum(g.hit_num for g in gs) / den if den else 0.0)
        self._obs_sparsity.set(
            float(np.mean([g.last.get("act_col_frac", 0.0) for g in gs])))
        self._obs_drift.set(max((g.drift_tvd for g in gs), default=0.0))
        self._obs_drifting.set(sum(1 for g in gs if g.drifting))

    # -------------------------------------------------------- surface --
    @staticmethod
    def _quantiles(hist: np.ndarray) -> dict:
        """p50/p90/p99 of the score distribution from a (possibly
        unnormalized) histogram over [0, 1]: linear interpolation inside
        the crossing bin."""
        total = float(hist.sum())
        if total <= 0:
            return {"p50": None, "p90": None, "p99": None}
        cum = np.cumsum(hist) / total
        out = {}
        for name, q in (("p50", 0.5), ("p90", 0.9), ("p99", 0.99)):
            b = int(np.searchsorted(cum, q))
            b = min(b, SCORE_BINS - 1)
            prev = float(cum[b - 1]) if b else 0.0
            span = float(cum[b]) - prev
            frac = (q - prev) / span if span > 0 else 0.5
            out[name] = round((b + frac) / SCORE_BINS, 4)
        return out

    def scorecard(self, gi: int) -> dict:
        """One group's JSON scorecard (the /health per-group unit)."""
        g = self._groups[gi]
        hit = g.hit_num / g.hit_den if g.hit_den else None
        problems = [k for k, v in (("pool_saturated", g.saturated),
                                   ("sparsity_collapsed", g.collapsed),
                                   ("score_drift", g.drifting)) if v]
        return {
            "group": int(gi),
            "ticks": g.ticks,
            "last_tick": g.last_tick,
            "occupancy": {
                "frac": round(g.last.get("seg_occ_frac", 0.0), 6),
                "hist": g.last.get("occ_hist", [0] * OCC_BINS),
                "bins": OCC_BINS,
            },
            "synapses": {
                "fill_frac": round(g.last.get("syn_frac", 0.0), 6),
                "connected_frac": round(
                    g.last.get("perm_conn_frac", 0.0), 6),
                "perm_hist": g.last.get("perm_hist", [0.0] * PERM_BINS),
                "bins": PERM_BINS,
            },
            "sparsity": {
                "active_col_frac": round(
                    g.last.get("act_col_frac", 0.0), 6),
                "pred_cell_frac": round(
                    g.last.get("pred_cell_frac", 0.0), 6),
                "expected_active_frac": round(
                    self.expected_active_frac, 6),
            },
            "hit_rate": None if hit is None else round(hit, 6),
            "score": {
                "hist": [round(float(x), 6) for x in g.fast],
                "bins": SCORE_BINS,
                "quantiles": self._quantiles(g.fast),
                "drift_tvd": round(g.drift_tvd, 6),
                "drifting": g.drifting,
            },
            "verdict": "ok" if not problems else ",".join(problems),
        }

    def snapshot(self) -> dict:
        """The GET /health body: fleet rollup + per-group scorecards.
        Also embedded in postmortem bundle summaries (obs/flight.py) and
        rendered by scripts/health_report.py — one schema everywhere."""
        # copy before iterating: the obs-server thread snapshots while
        # the loop thread's fold() may insert a just-claimed group's
        # slot (dict-size-changed RuntimeError otherwise — torn VALUES
        # are the documented contract, exceptions are not)
        gids = sorted(list(self._groups))
        gvals = list(self._groups.values())
        groups = [self.scorecard(gi) for gi in gids]
        den = sum(g.hit_den for g in gvals)
        num = sum(g.hit_num for g in gvals)
        attention = [g["group"] for g in groups if g["verdict"] != "ok"]
        return {
            "fleet": {
                "groups": len(groups),
                "ticks_folded": sum(g["ticks"] for g in groups),
                "pool_occupancy_max": max(
                    (g["occupancy"]["frac"] for g in groups), default=0.0),
                "hit_rate": round(num / den, 6) if den else None,
                "active_col_frac_mean": round(float(np.mean(
                    [g["sparsity"]["active_col_frac"] for g in groups])), 6)
                if groups else 0.0,
                "score_drift_max": max(
                    (g["score"]["drift_tvd"] for g in groups), default=0.0),
                "groups_attention": attention,
                "events_total": self.events_total,
                "events_by_kind": dict(sorted(self._events_by_kind.items())),
                "verdict": "ok" if not attention else "attention",
            },
            "groups": groups,
        }

    def stats(self) -> dict:
        """End-of-run accounting for the loop's stats dict (compact)."""
        snap_fleet = self.snapshot()["fleet"] if self._groups else {}
        return {
            "groups": len(self._groups),
            "ticks_folded": sum(
                g.ticks for g in list(self._groups.values())),
            "events": dict(sorted(self._events_by_kind.items())),
            **({"verdict": snap_fleet.get("verdict"),
                "pool_occupancy_max": snap_fleet.get("pool_occupancy_max"),
                "hit_rate": snap_fleet.get("hit_rate"),
                "score_drift_max": snap_fleet.get("score_drift_max")}
               if snap_fleet else {}),
        }


def bump_run_epoch(beside_path: str | None,
                   registry: TelemetryRegistry | None = None) -> int:
    """Increment and persist the run epoch; set ``rtap_obs_run_epoch``.

    The epoch lives in ``<beside_path>.epoch`` — beside the incident
    stream (the serve ``--alerts`` file), the one artifact a supervised
    restart chain shares. Each serve process start reads, increments,
    and atomically rewrites it, so the gauge is monotonic across
    restarts while every other counter resets with the process —
    dashboards join on it to tell restarts from rollovers. Returns the
    epoch (1-based; 0 when there is no path to persist beside —
    in-process-only serves have nothing to be continuous with).
    Corrupt/unreadable epoch files restart the count at 1, loudly never:
    continuity is best-effort diagnostics, not durability.
    """
    epoch = 0
    if beside_path:
        from rtap_tpu.service.shardpath import alert_sidecar_path

        path = alert_sidecar_path(beside_path, "epoch")
        try:
            with open(path) as f:
                epoch = int(json.load(f).get("epoch", 0))
        except (OSError, ValueError, AttributeError, TypeError):
            epoch = 0
        epoch += 1
        try:
            d = os.path.dirname(os.path.abspath(path))
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"epoch": epoch, "pid": os.getpid(),
                           "wall_time": time.time()}, f)
            os.replace(tmp, path)
        except OSError:
            pass  # the gauge still carries this process's view
    (registry or get_registry()).gauge(
        "rtap_obs_run_epoch",
        "monotonic serve run epoch (persisted beside the incident "
        "stream; bumped once per process start so dashboards can tell "
        "supervisor-restart counter resets from rollovers)").set(epoch)
    return epoch


def config_digest(config) -> str:
    """Stable short digest of a (nested, frozen-dataclass) config.

    Two serves score identically only if their configs match; the digest
    makes that comparable across the fleet without shipping the whole
    config. json with sorted keys over ``dataclasses.asdict`` is the
    canonical form; 12 hex chars is plenty for a label value.
    """
    import dataclasses
    import hashlib

    body = dataclasses.asdict(config) if dataclasses.is_dataclass(config) \
        else config
    canon = json.dumps(body, sort_keys=True, default=str)
    return hashlib.sha256(canon.encode()).hexdigest()[:12]


def set_build_info(*, role: str, shard: int, run_epoch: int,
                   config, registry: TelemetryRegistry | None = None) -> str:
    """Set the always-on ``rtap_obs_build_info`` identity gauge (value 1).

    The info-gauge idiom: identity rides the LABELS (role, shard,
    run_epoch, config_hash), the value is constant 1, so every scrape /
    snapshot / fleet push carries who this process is — dashboards and
    the fleet aggregator join per-member series on it instead of
    guessing identity from ports. Returns the config hash so serve can
    reuse it (the fleet HELLO carries the same identity). ``config`` may
    be a config dataclass or an already-computed hash string.
    """
    config_hash = config if isinstance(config, str) else \
        config_digest(config)
    (registry or get_registry()).gauge(
        "rtap_obs_build_info",
        "constant-1 identity gauge; the labels carry who this process "
        "is (role, shard, run_epoch, config_hash) so per-member series "
        "join without port-guessing",
        role=str(role), shard=str(int(shard)),
        run_epoch=str(int(run_epoch)), config_hash=config_hash).set(1)
    return config_hash
