"""rtap_tpu.obs — first-class telemetry for the serve stack.

One process-wide :class:`TelemetryRegistry` of counters, gauges, and
fixed-bucket latency histograms (obs/metrics.py); Prometheus-v0 text and
JSONL-snapshot exposition over localhost HTTP or to a file
(obs/expo.py); a tick watchdog that turns deadline misses, source
starvation, and checkpoint stalls into counters + structured JSONL
events (obs/watchdog.py); a per-tick span recorder exporting
Perfetto-loadable Chrome trace JSON (obs/trace.py); and a black-box
flight recorder that auto-dumps atomic postmortem bundles on
quarantine/degradation/miss-burst/crash (obs/flight.py,
docs/POSTMORTEM.md); detection-latency quantile sketches + stage
waterfalls (obs/latency.py) with operator-declared SLO burn-rate
alerting (obs/slo.py, docs/SLO.md). The serve hot paths (service/loop.py,
service/alerts.py, service/sources.py, service/checkpoint.py) emit
through this seam; docs/TELEMETRY.md catalogs every metric.
"""

from rtap_tpu.obs.expo import (
    ExpositionServer,
    default_snapshot_path,
    read_last_snapshot,
    render_prometheus,
    summarize_snapshot,
    write_snapshot,
)
from rtap_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    TelemetryRegistry,
    get_registry,
    log_buckets,
)
from rtap_tpu.obs.flight import FlightRecorder, validate_bundle
from rtap_tpu.obs.health import HealthTracker, bump_run_epoch, set_build_info
from rtap_tpu.obs.latency import LatencyTracker, QuantileSketch
from rtap_tpu.obs.slo import SloSpec, SloTracker, parse_slo
from rtap_tpu.obs.trace import TraceRecorder
from rtap_tpu.obs.watchdog import TickWatchdog

__all__ = [
    "Counter",
    "ExpositionServer",
    "FlightRecorder",
    "Gauge",
    "HealthTracker",
    "Histogram",
    "LatencyTracker",
    "QuantileSketch",
    "SloSpec",
    "SloTracker",
    "TelemetryRegistry",
    "TickWatchdog",
    "TraceRecorder",
    "bump_run_epoch",
    "default_snapshot_path",
    "get_registry",
    "log_buckets",
    "parse_slo",
    "read_last_snapshot",
    "render_prometheus",
    "set_build_info",
    "summarize_snapshot",
    "validate_bundle",
    "write_snapshot",
]
