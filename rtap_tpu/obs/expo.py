"""Telemetry exposition: Prometheus v0 text + JSONL snapshots + HTTP server.

Two consumption shapes, one registry (obs/metrics.py):

- **Pull**: :class:`ExpositionServer` serves ``GET /metrics`` (Prometheus
  text format 0.0.4) and ``GET /snapshot`` (one JSON object) from a
  background thread on a localhost TCP port — the same ephemeral-port,
  ``.address``, context-manager style as the serve path's TcpJsonlSource.
- **File**: :func:`write_snapshot` appends one JSON line per call — the
  no-network surface for hw sessions (the tunnel host has no scrape
  infrastructure; scripts/hw_session.py points children at a per-step
  snapshot path via ``RTAP_OBS_SNAPSHOT`` and reads the last line back
  instead of scraping stdout).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry

__all__ = [
    "ExpositionServer",
    "default_snapshot_path",
    "read_last_snapshot",
    "render_prometheus",
    "summarize_snapshot",
    "write_snapshot",
]

#: children inherit this from a session runner (scripts/hw_session.py): the
#: default file the final snapshot lands in when no explicit path is given
SNAPSHOT_ENV = "RTAP_OBS_SNAPSHOT"


def default_snapshot_path() -> str | None:
    return os.environ.get(SNAPSHOT_ENV) or None


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render as integers."""
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def _labelstr(labels: dict[str, str], extra: tuple[str, str] | None = None) -> str:
    items = sorted(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r"\""))
        for k, v in items
    )
    return "{%s}" % body


def render_prometheus(registry: TelemetryRegistry | None = None) -> str:
    """The registry as Prometheus text exposition format 0.0.4.

    Counters/gauges are one sample per (name, labels); histograms expand to
    the standard cumulative ``_bucket{le=...}`` series plus ``_sum`` and
    ``_count``. Families (shared name, distinct labels) share one
    HELP/TYPE header.
    """
    registry = registry or get_registry()
    lines: list[str] = []
    seen_header: set[str] = set()
    for inst in registry.collect():
        if inst.name not in seen_header:
            seen_header.add(inst.name)
            help_text = registry.help_for(inst.name)
            if help_text:
                lines.append("# HELP %s %s" % (
                    inst.name,
                    help_text.replace("\\", r"\\").replace("\n", r"\n")))
            lines.append("# TYPE %s %s" % (inst.name, inst.kind))
        if inst.kind == "histogram":
            merged = inst._merged()
            cum = 0
            for edge, c in zip(inst.edges, merged.counts):
                cum += int(c)
                lines.append("%s_bucket%s %s" % (
                    inst.name, _labelstr(inst.labels, ("le", _fmt(edge))),
                    cum))
            total = cum + int(merged.counts[-1])
            lines.append("%s_bucket%s %s" % (
                inst.name, _labelstr(inst.labels, ("le", "+Inf")), total))
            lines.append("%s_sum%s %s" % (
                inst.name, _labelstr(inst.labels), _fmt(merged.sum)))
            lines.append("%s_count%s %s" % (
                inst.name, _labelstr(inst.labels), total))
        else:
            lines.append("%s%s %s" % (
                inst.name, _labelstr(inst.labels), _fmt(inst.value)))
    return "\n".join(lines) + "\n"


def write_snapshot(path: str | None = None,
                   registry: TelemetryRegistry | None = None) -> dict | None:
    """Append one JSON snapshot line to `path` (default: $RTAP_OBS_SNAPSHOT;
    no-op returning None when neither is set). Returns the snapshot dict.

    The append is tmp-file + atomic rename (read the existing bytes,
    write them plus the new line to a temp sibling, ``os.replace``):
    a scraper or soak harness polling the file mid-write can never read
    a torn half-line — the same discipline as postmortem bundles and
    the correlator sidecar. Snapshot files are one line per serve exit
    (plus per-step session lines), so the copy is a few KB, not a log.
    """
    path = path or default_snapshot_path()
    if not path:
        return None
    snap = (registry or get_registry()).snapshot()
    line = (json.dumps(snap) + "\n").encode()
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # an flock sidecar serializes concurrent writers (two serve
    # processes sharing an ambient $RTAP_OBS_SNAPSHOT — e.g. an HA
    # pair on one host — must not read-modify-replace over each other
    # and silently drop an exit line the old O_APPEND write kept)
    import fcntl

    with open(path + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            with open(path, "rb") as f:
                prior = f.read()
            if prior and not prior.endswith(b"\n"):
                prior += b"\n"  # heal a torn pre-atomic writer's tail
        except FileNotFoundError:
            prior = b""
        except OSError:
            # the file EXISTS but won't read (transient EIO/EACCES):
            # fall back to a plain append — a possibly-torn extra line
            # beats replacing the accumulated history with nothing
            with open(path, "ab") as f:
                f.write(line)
            return snap
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(prior + line)
        os.replace(tmp, path)
    return snap


def read_last_snapshot(path: str) -> dict | None:
    """Last parseable snapshot line of a JSONL snapshot file (None when the
    file is missing/empty — callers treat absence as 'step emitted none')."""
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    for line in reversed(lines):
        line = line.strip()
        if not line:
            continue
        try:
            snap = json.loads(line)
        except ValueError:
            continue
        if isinstance(snap, dict) and "metrics" in snap:
            return snap
    return None


def summarize_snapshot(snap: dict) -> dict:
    """Flatten a snapshot into a compact {metric_key: scalar-ish} dict for
    artifacts and one-line verdicts: counters/gauges -> value; histograms ->
    {count, sum, mean, max}. Label sets fold into the key as k=v pairs."""
    out: dict = {}
    for m in snap.get("metrics", []):
        key = m["name"]
        labels = m.get("labels") or {}
        if labels:
            key += "{" + ",".join(f"{k}={v}" for k, v in sorted(labels.items())) + "}"
        v = m["value"]
        if isinstance(v, dict):  # histogram
            count = int(v.get("count", 0))
            s = float(v.get("sum", 0.0))
            h = {"count": count, "sum": round(s, 6)}
            if count:
                h["mean"] = round(s / count, 6)
                if "max" in v:
                    h["max"] = round(float(v["max"]), 6)
            out[key] = h
        else:
            out[key] = v
    return out


class _Handler(BaseHTTPRequestHandler):
    server_version = "rtap-obs/0"

    def do_GET(self):  # noqa: N802 — http.server API
        path, _, query = self.path.partition("?")
        if path in ("/metrics", "/"):
            body = render_prometheus(self.server.registry).encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif path == "/snapshot":
            body = (json.dumps(self.server.registry.snapshot()) + "\n").encode()
            ctype = "application/json"
        elif path == "/trace":
            # the span recorder's timeline as Chrome trace-event JSON
            # (save the body and open it in ui.perfetto.dev). ?last=N
            # windows to the last N ticks (default 120).
            tr = getattr(self.server, "trace", None)
            if tr is None:
                self.send_error(404, "tracing not enabled (serve --trace-out"
                                     " / --postmortem-dir)")
                return
            try:
                from urllib.parse import parse_qs

                last = int(parse_qs(query).get("last", ["120"])[0])
            except (ValueError, IndexError):
                self.send_error(400, "bad ?last= value")
                return
            body = (json.dumps(tr.chrome_trace(last_ticks=last))
                    + "\n").encode()
            ctype = "application/json"
        elif path == "/health":
            # fleet rollup + per-group model-health scorecards (ISSUE 6):
            # occupancy, sparsity, hit rate, score quantiles, drift
            # verdict — the HealthTracker's point-in-time snapshot (the
            # loop thread folds concurrently; diagnostic read, not a
            # consistent cut — same contract as /trace)
            ht = getattr(self.server, "health", None)
            if ht is None:
                self.send_error(404, "health reducers not enabled "
                                     "(serve --health)")
                return
            body = (json.dumps(ht.snapshot()) + "\n").encode()
            ctype = "application/json"
        elif path == "/predict":
            # per-stream divergence trajectories, alarmed streams, and
            # open predicted-blast windows (ISSUE 16, rtap_tpu/predict/):
            # the PredictTracker's point-in-time snapshot — diagnostic
            # read, same contract as /health
            pt = getattr(self.server, "predict", None)
            if pt is None:
                self.send_error(404, "predictive horizon not enabled "
                                     "(serve --predict)")
                return
            body = (json.dumps(pt.snapshot()) + "\n").encode()
            ctype = "application/json"
        elif path == "/incidents":
            # cluster-level incident records + open correlation windows
            # (ISSUE 9, rtap_tpu/correlate/): the correlator's point-in-
            # time snapshot — same diagnostic-read contract as /health
            co = getattr(self.server, "correlator", None)
            if co is None:
                self.send_error(404, "incident correlation not enabled "
                                     "(serve --topology)")
                return
            body = (json.dumps(co.snapshot()) + "\n").encode()
            ctype = "application/json"
        elif path == "/latency":
            # detection-latency stage waterfalls + windowed quantile
            # sketches (ISSUE 11, obs/latency.py): the tracker's point-
            # in-time snapshot — diagnostic read, same contract as
            # /health (the loop thread folds concurrently)
            lt = getattr(self.server, "latency", None)
            if lt is None:
                self.send_error(404, "latency tracking not enabled "
                                     "(serve --latency)")
                return
            body = (json.dumps(lt.snapshot()) + "\n").encode()
            ctype = "application/json"
        elif path == "/slo":
            # declared SLOs, live burn rates, and the current verdict
            # (obs/slo.py; docs/SLO.md is the runbook)
            sl = getattr(self.server, "slo", None)
            if sl is None:
                self.send_error(404, "no SLOs declared (serve --slo "
                                     "NAME=TARGET@pQ)")
                return
            body = (json.dumps(sl.snapshot()) + "\n").encode()
            ctype = "application/json"
        elif path == "/healthz":
            # liveness for external supervision probes (k8s-style):
            # 200 with {"ok": true} while the loop ticked within
            # stale_after_s; 503 before the first tick and once the
            # last-tick age exceeds it (docs/TELEMETRY.md contract).
            # Reads registry gauges only — never perturbs state.
            import time as _time

            vals = {}
            for inst in self.server.registry.collect():
                if inst.kind == "gauge" and inst.name in (
                        "rtap_obs_last_tick_unixtime",
                        "rtap_obs_run_epoch",
                        "rtap_obs_degradation_level"):
                    vals[inst.name] = inst.value
            stale_after = float(getattr(
                self.server, "healthz_stale_after_s", 30.0))
            last = vals.get("rtap_obs_last_tick_unixtime")
            age = (_time.time() - last) if last else None
            ok = age is not None and age <= stale_after
            body = (json.dumps({
                "ok": ok,
                "run_epoch": int(vals.get("rtap_obs_run_epoch", 0)),
                "last_tick_age_s": round(age, 3)
                if age is not None else None,
                "degradation_level": int(vals.get(
                    "rtap_obs_degradation_level", 0)),
                "stale_after_s": stale_after,
            }) + "\n").encode()
            self.send_response(200 if ok else 503)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        elif path.startswith("/fleet/"):
            # the fleet aggregator's merged views (ISSUE 19,
            # rtap_tpu/fleet/): counters summed across members, gauges
            # labeled per member, quantiles from MERGED sketches, one
            # fleet SLO verdict, member roster + incident rollup —
            # point-in-time diagnostic reads, same contract as /health
            ag = getattr(self.server, "fleet", None)
            if ag is None:
                self.send_error(404, "fleet aggregation not enabled "
                                     "(serve --fleet-listen PORT)")
                return
            route = {
                "/fleet/metrics": ag.fleet_metrics,
                "/fleet/health": ag.fleet_health,
                "/fleet/latency": ag.fleet_latency,
                "/fleet/slo": ag.fleet_slo,
                "/fleet/incidents": ag.fleet_incidents,
                "/fleet/members": ag.members_view,
                "/fleet/events": ag.events_view,
                "/fleet/snapshot": ag.snapshot,
            }.get(path)
            if route is None:
                self.send_error(404)
                return
            body = (json.dumps(route()) + "\n").encode()
            ctype = "application/json"
        elif path == "/postmortem":
            # on-demand flight-recorder dump; returns the bundle path (or
            # null when throttled). GET because it is an operator poke on
            # a localhost-only diagnostic server, not a REST resource.
            fl = getattr(self.server, "flight", None)
            if fl is None:
                self.send_error(404, "flight recorder not enabled "
                                     "(serve --postmortem-dir)")
                return
            body = (json.dumps({"bundle": fl.dump("on_demand")})
                    + "\n").encode()
            ctype = "application/json"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # scrapes must not spam the serve stderr
        pass


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class ExpositionServer:
    """Localhost telemetry endpoint on a background daemon thread.

    ``port=0`` binds ephemeral (the serve/TCP path's orphan-proof style);
    the bound address is ``.address``. Start/stop via context manager or
    ``start()``/``close()``. Scrape ``/metrics`` for Prometheus text,
    ``/snapshot`` for the JSON snapshot; with a ``trace`` recorder
    attached, ``/trace?last=N`` serves the Perfetto-loadable timeline,
    with a ``flight`` recorder, ``/postmortem`` dumps a bundle on
    demand, with a ``correlator`` (rtap_tpu/correlate/), ``/incidents``
    serves recent cluster-level incidents + open correlation windows,
    and with a ``health`` tracker (obs/health.py),
    ``/health`` serves the fleet rollup + per-group model scorecards
    (rings/scorecards are written lock-free by the loop, so a
    concurrent read is point-in-time diagnostic data, not a consistent
    snapshot). With a ``latency`` tracker (obs/latency.py),
    ``/latency`` serves the stage waterfalls + windowed quantiles, and
    with an ``slo`` tracker (obs/slo.py), ``/slo`` serves the declared
    SLOs' live burn rates and verdict, and with a ``predict`` tracker
    (rtap_tpu/predict/), ``/predict`` serves the divergence
    trajectories, alarmed streams, and open predicted-blast windows.
    With a ``fleet`` aggregator (rtap_tpu/fleet/), the ``/fleet/*``
    routes serve the merged cross-process views — metrics, health,
    latency, slo, incidents, members, events, snapshot.
    ``/healthz`` is always routed:
    a liveness probe returning 200 while the loop ticked within
    ``healthz_stale_after_s`` seconds, 503 otherwise
    (docs/TELEMETRY.md documents the contract).
    """

    def __init__(self, registry: TelemetryRegistry | None = None,
                 host: str = "127.0.0.1", port: int = 0,
                 trace=None, flight=None, health=None, correlator=None,
                 latency=None, slo=None, predict=None, fleet=None,
                 healthz_stale_after_s: float = 30.0):
        self.registry = registry or get_registry()
        self._server = _Server((host, port), _Handler)
        self._server.registry = self.registry
        self._server.trace = trace
        self._server.flight = flight
        self._server.health = health
        self._server.correlator = correlator
        self._server.latency = latency
        self._server.slo = slo
        self._server.predict = predict
        self._server.fleet = fleet
        self._server.healthz_stale_after_s = float(healthz_stale_after_s)
        self.address = self._server.server_address  # (host, bound port)
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="rtap-obs-http", daemon=True)

    def start(self) -> "ExpositionServer":
        self._thread.start()
        return self

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        # shutdown() returns once serve_forever exits, so the join is
        # immediate — but without it the thread object outlives close()
        # and the conftest leak fixture (rightly) calls that a leak
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "ExpositionServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
