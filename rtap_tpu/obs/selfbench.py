"""Obs self-benchmark: what does instrumentation cost the tick loop?

The acceptance bar for the telemetry seam is registry overhead <= 1% of the
tick budget. A serve tick at the flagship shape emits a few dozen
instrument operations (6 phase-histogram observes, a tick-latency observe,
2-4 counter incs, a gauge set, plus per-group alert accounting), so the
budget math is ``ops_per_tick * ns_per_op`` vs ``cadence_s``. This module
measures ns_per_op on the running host; bench.py exposes it as
``bench.py --obs-bench`` and tests/unit/test_obs.py pins the 1% bar.
"""

from __future__ import annotations

import time

from rtap_tpu.obs.metrics import TelemetryRegistry

__all__ = ["measure", "measure_trace", "measure_journal", "measure_health",
           "measure_correlate", "measure_latency", "measure_predict",
           "measure_fleet",
           "GATE_MEASURES", "GATE_BUDGET_FRAC",
           "OPS_PER_TICK", "TRACE_SPANS_PER_TICK",
           "HEALTH_FOLDS_PER_TICK", "CORRELATE_ALERTS_PER_TICK",
           "LATENCY_OBSERVES_PER_TICK", "PREDICT_FOLDS_PER_TICK",
           "FLEET_PUSHES_PER_TICK"]

#: instrument operations a serve tick costs at the production shape (six
#: phase observes + tick latency observe + ticks/scored/alert counters +
#: streams gauge + watchdog deadline check), rounded up for headroom
OPS_PER_TICK = 32

#: span-ring appends a serve tick costs at the production multi-group
#: shape: the tick span + six phase spans + one dispatch and one collect
#: child span per group at 16 groups (7 + 2*16 = 39), rounded up
TRACE_SPANS_PER_TICK = 40

#: HealthTracker.fold calls a serve tick costs at the production
#: multi-group shape: one per collected chunk per group, 16 groups
HEALTH_FOLDS_PER_TICK = 16

#: alert folds a correlating serve tick is budgeted for (ISSUE 9): an
#: ACTIVE incident across a whole 16-node blast radius at 2 metrics per
#: node pages ~32 streams at once; healthy ticks fold zero, so this is
#: the storm-ceiling shape, not the steady state
CORRELATE_ALERTS_PER_TICK = 32

#: per-alert detect observations a latency-tracking tick is budgeted
#: for (ISSUE 11): the same 32-stream alert-storm ceiling as the
#: correlator, on top of the per-tick record_tick + SLO evaluation
LATENCY_OBSERVES_PER_TICK = 32

#: PredictTracker.fold calls a serve tick costs at the production
#: multi-group shape (ISSUE 16): one per collected chunk per group, 16
#: groups — the same shape as the health folds they ride beside
PREDICT_FOLDS_PER_TICK = 16

#: fleet snapshot builds a serve tick is budgeted for (ISSUE 19): the
#: soak children push every cadence/2 (two full snapshot builds per
#: tick); production serve defaults to one push per second against a
#: 1 s cadence — the gate budgets the denser soak shape
FLEET_PUSHES_PER_TICK = 2


def _time_op(fn, n: int) -> float:
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n


def measure(n: int = 50_000, cadence_s: float = 1.0) -> dict:
    """Per-operation cost of the three write paths on a private registry,
    plus the projected per-tick overhead fraction at `cadence_s`."""
    reg = TelemetryRegistry()
    c = reg.counter("selfbench_counter_total")
    g = reg.gauge("selfbench_gauge")
    h = reg.histogram("selfbench_seconds")
    # warm the per-thread cells/shards out of the measurement (first op per
    # thread allocates; steady state is what the tick loop pays)
    c.inc(); g.set(1.0); h.observe(0.01)

    counter_s = _time_op(lambda: c.inc(), n)
    gauge_s = _time_op(lambda: g.set(2.5), n)
    hist_s = _time_op(lambda: h.observe(0.0123), n)
    worst = max(counter_s, gauge_s, hist_s)
    per_tick_s = OPS_PER_TICK * worst
    return {
        "counter_ns": round(counter_s * 1e9, 1),
        "gauge_ns": round(gauge_s * 1e9, 1),
        "histogram_observe_ns": round(hist_s * 1e9, 1),
        "ops_per_tick": OPS_PER_TICK,
        "per_tick_overhead_us": round(per_tick_s * 1e6, 2),
        "per_tick_overhead_frac": per_tick_s / cadence_s,
        "cadence_s": cadence_s,
    }


def measure_trace(n: int = 50_000, cadence_s: float = 1.0,
                  n_groups: int = 16) -> dict:
    """Trace-ring + flight-recorder hot-path cost, same protocol as
    :func:`measure`: per-op nanoseconds on a private recorder, projected
    to a tick at the production multi-group shape (ISSUE 4 acceptance:
    tracing + flight recording together stay <= 1% of the tick budget).

    A tick costs ``TRACE_SPANS_PER_TICK`` span appends plus ONE flight
    ``record_tick`` (instants ride event paths — rare by construction,
    measured anyway for the record)."""
    from rtap_tpu.obs.flight import FlightRecorder
    from rtap_tpu.obs.metrics import TelemetryRegistry
    from rtap_tpu.obs.trace import TraceRecorder

    tr = TraceRecorder(capacity=4096)
    t0 = time.perf_counter()
    # warm the shard + name intern out of the measurement (first-op cost)
    tr.add_span("dispatch", 0, t0, 0.001, group=3)
    tr.add_instant("missed_tick", 0, {"elapsed_s": 1.2})
    span_s = _time_op(lambda: tr.add_span("dispatch", 1, t0, 0.001, group=3),
                      n)
    n_inst = max(1, n // 10)
    inst_s = _time_op(
        lambda: tr.add_instant("missed_tick", 1, {"elapsed_s": 1.2}), n_inst)

    fl = FlightRecorder(trace=tr, n_ticks=256,
                        registry=TelemetryRegistry())
    phases = {p: 0.001 for p in ("source", "membership", "dispatch",
                                 "collect", "emit", "checkpoint")}
    scored = [n_groups] * n_groups
    tick = [0]

    def _rt():
        tick[0] += 1
        fl.record_tick(tick[0], 0.01, phases, scored, False)

    _rt()  # size the rings out of the measurement
    rt_s = _time_op(_rt, max(1, n // 5))

    per_tick_s = TRACE_SPANS_PER_TICK * span_s + rt_s
    return {
        "trace_span_ns": round(span_s * 1e9, 1),
        "trace_instant_ns": round(inst_s * 1e9, 1),
        "flight_record_tick_ns": round(rt_s * 1e9, 1),
        "spans_per_tick": TRACE_SPANS_PER_TICK,
        "n_groups": n_groups,
        "per_tick_overhead_us": round(per_tick_s * 1e6, 2),
        "per_tick_overhead_frac": per_tick_s / cadence_s,
        "cadence_s": cadence_s,
    }


def measure_health(n: int = 2000, cadence_s: float = 1.0,
                   n_groups: int = HEALTH_FOLDS_PER_TICK) -> dict:
    """Model-health host-path cost, same protocol as :func:`measure`:
    per-fold nanoseconds of ``HealthTracker.fold`` on a private tracker
    fed realistic per-tick leaves, projected to a tick at the
    production multi-group shape (one fold per group per tick at 16
    groups). The DEVICE-side reducer cost is a property of the compiled
    step and is measured on silicon by the ``r9_health`` hw-session
    step; the host fold is what the loop thread pays every tick, and
    ISSUE 6 gates it <= 1% of the tick budget alongside the metric/
    trace/journal bars (``bench.py --obs-bench``)."""
    import numpy as np

    from rtap_tpu.config import cluster_preset
    from rtap_tpu.obs.health import HealthTracker
    from rtap_tpu.obs.metrics import TelemetryRegistry
    from rtap_tpu.ops.health_tpu import (
        OCC_BINS, PERM_BINS, SCORE_BINS, health_nbytes,
    )

    ht = HealthTracker(cluster_preset(), registry=TelemetryRegistry())
    rng = np.random.default_rng(0)
    leaves = {
        "occ_hist": rng.integers(0, 64, (1, OCC_BINS), dtype=np.int32),
        "seg_occ_frac": np.float32([0.4]),
        "syn_frac": np.float32([0.3]),
        "perm_hist": rng.random((1, PERM_BINS), np.float32),
        "perm_conn_frac": np.float32([0.5]),
        "act_col_frac": np.float32([0.02]),
        "pred_cell_frac": np.float32([0.01]),
        "hit_num": np.float32([900.0]),
        "hit_den": np.float32([1024.0]),
        "score_hist": rng.integers(0, 64, (1, SCORE_BINS), dtype=np.int32),
        "scored": np.int32([1024]),
    }
    gi = [0]

    def _fold():
        gi[0] = (gi[0] + 1) % n_groups
        ht.fold(gi[0], leaves, tick=gi[0])

    _fold()  # warm the group slot + instrument shards out of the timing
    fold_s = _time_op(_fold, n)
    snap_s = _time_op(ht.snapshot, max(1, n // 20))
    # one fold per group per tick: the projection must follow the shape
    # actually measured, not the 16-group default
    per_tick_s = n_groups * fold_s
    return {
        "health_fold_us": round(fold_s * 1e6, 2),
        "health_snapshot_us": round(snap_s * 1e6, 2),
        "folds_per_tick": n_groups,
        "n_groups": n_groups,
        "leaf_bytes_per_group_tick": health_nbytes(),
        "per_tick_overhead_us": round(per_tick_s * 1e6, 2),
        "per_tick_overhead_frac": per_tick_s / cadence_s,
        "cadence_s": cadence_s,
    }


def measure_journal(n: int = 2000, cadence_s: float = 1.0,
                    n_streams: int = 1024) -> dict:
    """Write-ahead-journal hot-path cost, same protocol as
    :func:`measure`: a serve tick pays ONE tick-row append (format +
    write + flush-to-kernel, fsync policy ``os`` — the default) plus one
    alert-cursor append per emitted chunk, measured on a private journal
    in a temp dir at the production per-chip row width. ISSUE 5
    acceptance: journaling stays <= 1% of the tick budget
    (``bench.py --obs-bench`` gates it alongside the trace/flight bars).
    """
    import shutil
    import tempfile

    import numpy as np

    from rtap_tpu.resilience.journal import TickJournal

    d = tempfile.mkdtemp(prefix="rtap_selfbench_journal_")
    try:
        j = TickJournal(d, fsync="os")
        row = np.full(n_streams, 31.5, np.float32)
        # warm the segment handle + first-write path out of the timing
        j.append_tick(0, 1_700_000_000, row)
        j.append_cursor(0, 0)
        i = [0]

        def _tick():
            i[0] += 1
            j.append_tick(i[0], 1_700_000_000 + i[0], row)

        tick_s = _time_op(_tick, n)
        cursor_s = _time_op(lambda: j.append_cursor(i[0], 123456), n)
        rotations = j.rotations
        j.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    per_tick_s = tick_s + cursor_s
    return {
        "journal_tick_append_us": round(tick_s * 1e6, 2),
        "journal_cursor_append_us": round(cursor_s * 1e6, 2),
        "n_streams": n_streams,
        "row_bytes": int(row.nbytes),
        "segment_rotations": rotations,
        "fsync": "os",
        "per_tick_overhead_us": round(per_tick_s * 1e6, 2),
        "per_tick_overhead_frac": per_tick_s / cadence_s,
        "cadence_s": cadence_s,
    }


def measure_correlate(n: int = 20_000, cadence_s: float = 1.0,
                      n_alerts: int = CORRELATE_ALERTS_PER_TICK,
                      n_clusters: int = 8) -> dict:
    """Incident-correlator hot-path cost (ISSUE 9), same protocol as
    :func:`measure`: per-op nanoseconds of ``observe_alert`` (the fold)
    and ``on_tick`` (the window-close scan) on a private correlator with
    ``n_clusters`` clusters kept PERMANENTLY open — the storm ceiling,
    where every tick both folds a full blast-radius worth of alerts and
    scans every open window. A healthy tick pays one near-empty
    ``on_tick`` only; this projects the worst case, and ``bench.py
    --obs-bench`` gates it <= 1% of the tick budget alongside the
    metric/trace/journal/health bars."""
    from rtap_tpu.correlate import IncidentCorrelator, TopologyMap

    co = IncidentCorrelator(
        TopologyMap.infer(), window_s=3600, min_streams=3,
        sink=lambda _rec: None, registry=TelemetryRegistry())
    streams = [f"svc{c:02d}-{i:02d}.cpu"
               for c in range(n_clusters) for i in range(4)]
    i = [0]

    def _fold():
        i[0] += 1
        co.observe_alert(f"a{i[0]}", streams[i[0] % len(streams)],
                         1_700_000_000, top_fields=None)

    _fold()  # open the windows / warm instrument shards out of the timing
    fold_s = _time_op(_fold, n)
    # the scan walks n_clusters open windows and closes none (window_s
    # holds them open) — the recurring per-tick cost, not the rare close
    tick_s = _time_op(lambda: co.on_tick(1_700_000_000), n)
    per_tick_s = n_alerts * fold_s + tick_s
    return {
        "correlate_fold_us": round(fold_s * 1e6, 2),
        "correlate_on_tick_us": round(tick_s * 1e6, 2),
        "alerts_per_tick": n_alerts,
        "open_clusters": n_clusters,
        "per_tick_overhead_us": round(per_tick_s * 1e6, 2),
        "per_tick_overhead_frac": per_tick_s / cadence_s,
        "cadence_s": cadence_s,
    }


def measure_latency(n: int = 20_000, cadence_s: float = 1.0,
                    n_alerts: int = LATENCY_OBSERVES_PER_TICK) -> dict:
    """Detection-latency instrumentation cost (ISSUE 11), same protocol
    as :func:`measure`: per-op nanoseconds of the quantile-sketch
    observe (the per-alert detect path) and the full per-tick fold
    (``LatencyTracker.record_tick`` + ``SloTracker.on_tick`` with two
    declared SLOs — the stage sketches, the waterfall build, the lag
    probes, and the burn-rate evaluation), projected to a tick at the
    alert-storm ceiling. Registered in :data:`GATE_MEASURES`, so
    ``bench.py --obs-bench`` gates it <= 1% of the tick budget alongside
    every other obs instrument."""
    import numpy as np

    from rtap_tpu.obs.latency import LatencyTracker
    from rtap_tpu.obs.slo import SloTracker, parse_slo

    reg = TelemetryRegistry()
    tracker = LatencyTracker(window_ticks=120, cadence_s=cadence_s,
                             registry=reg)
    slo = SloTracker([parse_slo("detect=2s@p99"),
                      parse_slo("tick=1s@p99")],
                     cadence_s=cadence_s, registry=reg,
                     quantile_source=tracker.quantile)
    tracker.slo = slo
    tracker.lag_providers["repl_ack_ticks"] = lambda _t, _ts: 3.0
    lags = np.full(1, 0.123)
    phases = {p: 0.001 for p in ("source", "membership", "dispatch",
                                 "collect", "emit", "checkpoint")}
    tick = [0]

    def _rt():
        tick[0] += 1
        tracker.record_tick(tick[0], 1_700_000_000 + tick[0], phases,
                            0.01, poll_wall=1_700_000_000.5 + tick[0])
        slo.on_tick(tick[0])

    # warm the sketch shards / instrument cells out of the measurement
    tracker.observe_detect(lags)
    _rt()
    observe_s = _time_op(lambda: tracker.observe_detect(lags), n)
    rt_s = _time_op(_rt, max(1, n // 10))
    per_tick_s = n_alerts * observe_s + rt_s
    return {
        "latency_observe_ns": round(observe_s * 1e9, 1),
        "latency_record_tick_us": round(rt_s * 1e6, 2),
        "alerts_per_tick": n_alerts,
        "per_tick_overhead_us": round(per_tick_s * 1e6, 2),
        "per_tick_overhead_frac": per_tick_s / cadence_s,
        "cadence_s": cadence_s,
    }


def measure_predict(n: int = 2000, cadence_s: float = 1.0,
                    n_groups: int = PREDICT_FOLDS_PER_TICK,
                    n_streams: int = 1024) -> dict:
    """Predictive-horizon host-path cost (ISSUE 16), same protocol as
    :func:`measure`: per-fold nanoseconds of ``PredictTracker.fold`` on
    a private tracker fed realistic per-(group, tick) leaves at the
    production group width, projected to a tick at the multi-group
    shape (one fold per group per tick at 16 groups, beside the health
    folds). The DEVICE-side reducer cost is a property of the compiled
    step and is measured on silicon by the ``r15_predict`` hw-session
    step; the host fold is what the loop thread pays, and ISSUE 16
    gates it <= 1% of the tick budget alongside every other obs
    instrument (``bench.py --obs-bench``)."""
    import numpy as np

    from rtap_tpu.models.oracle.predict import predict_nbytes
    from rtap_tpu.predict import PredictTracker

    pt = PredictTracker(horizon=8, registry=TelemetryRegistry(),
                        threshold=0.35, min_ticks=12)
    rng = np.random.default_rng(0)
    miss = rng.random(n_streams).astype(np.float32) * 0.3
    leaves = {
        "overlap": (1.0 - miss)[None, :],
        "miss_ewma": miss[None, :],
        "pred_col_frac": np.full((1, n_streams), 0.04, np.float32),
        "scored": np.ones((1, n_streams), bool),
    }
    ids = [f"node{i:05d}.cpu" for i in range(n_streams)]
    gi = [0]

    def _fold():
        gi[0] = (gi[0] + 1) % n_groups
        pt.fold(gi[0], leaves, tick=gi[0], ids=ids)

    _fold()  # warm the group slot + instrument shards out of the timing
    fold_s = _time_op(_fold, n)
    snap_s = _time_op(pt.snapshot, max(1, n // 20))
    per_tick_s = n_groups * fold_s
    return {
        "predict_fold_us": round(fold_s * 1e6, 2),
        "predict_snapshot_us": round(snap_s * 1e6, 2),
        "folds_per_tick": n_groups,
        "n_groups": n_groups,
        "n_streams": n_streams,
        "leaf_bytes_per_group_tick": predict_nbytes(n_streams),
        "per_tick_overhead_us": round(per_tick_s * 1e6, 2),
        "per_tick_overhead_frac": per_tick_s / cadence_s,
        "cadence_s": cadence_s,
    }


def measure_fleet(n: int = 2000, cadence_s: float = 1.0,
                  n_pushes: int = FLEET_PUSHES_PER_TICK) -> dict:
    """Fleet-publisher cost (ISSUE 19), same protocol as :func:`measure`:
    per-op nanoseconds of ``note_tick`` (the ONLY fleet operation on the
    tick path — one guarded int store) and of the full snapshot build +
    wire pack the push thread pays per interval (registry snapshot,
    lossless sketch states, SLO window counts — GIL time the loop thread
    contends with even though the send itself is off-path), projected to
    a tick at the soak push density (``push_interval = cadence/2`` ->
    two snapshot builds per tick). The publisher is never started: the
    measurement is the build+pack cost, not socket I/O. Registered in
    :data:`GATE_MEASURES`, so ``bench.py --obs-bench`` gates it <= 1% of
    the tick budget alongside every other obs instrument."""
    from rtap_tpu.fleet.member import FleetPublisher
    from rtap_tpu.fleet.protocol import FLEET_SNAP, pack_fleet
    from rtap_tpu.obs.latency import LatencyTracker
    from rtap_tpu.obs.slo import SloTracker, parse_slo

    reg = TelemetryRegistry()
    # a realistic push payload: a serving registry plus armed latency/
    # SLO trackers with FULL sketch windows (state() walks every bucket
    # array — empty sketches would understate the steady-state cost)
    reg.counter("rtap_obs_ticks_total").inc(1000)
    reg.counter("rtap_obs_scored_total").inc(64_000)
    reg.gauge("rtap_obs_streams_active").set(1024.0)
    tracker = LatencyTracker(window_ticks=120, cadence_s=cadence_s,
                             registry=reg)
    slo = SloTracker([parse_slo("tick=1s@p99")], cadence_s=cadence_s,
                     registry=reg, quantile_source=tracker.quantile)
    tracker.slo = slo
    phases = {p: 0.001 for p in ("source", "membership", "dispatch",
                                 "collect", "emit", "checkpoint")}
    for t in range(120):
        tracker.record_tick(t, 1_700_000_000 + t, phases, 0.01)
        slo.on_tick(t)
    pub = FleetPublisher(("127.0.0.1", 1), "selfbench", registry=reg,
                         latency=tracker, slo=slo,
                         push_interval_s=max(0.001, cadence_s / 2))
    pub.note_tick(0)  # warm the lock path out of the measurement
    note_s = _time_op(lambda: pub.note_tick(1), 50_000)

    frame_bytes = [0]

    def _push():
        frame_bytes[0] = len(pack_fleet(FLEET_SNAP, pub._snap()))

    _push()  # warm the registry/sketch snapshot paths
    snap_s = _time_op(_push, n)
    per_tick_s = note_s + n_pushes * snap_s
    return {
        "fleet_note_tick_ns": round(note_s * 1e9, 1),
        "fleet_snap_pack_us": round(snap_s * 1e6, 2),
        "snap_frame_bytes": frame_bytes[0],
        "pushes_per_tick": n_pushes,
        "per_tick_overhead_us": round(per_tick_s * 1e6, 2),
        "per_tick_overhead_frac": per_tick_s / cadence_s,
        "cadence_s": cadence_s,
    }


#: THE obs-bench gate registry (ISSUE 11 satellite): every self-
#: benchmarked instrument surface, each gated <= ``budget_frac`` of the
#: tick budget by ``bench.py --obs-bench`` and the tier-1 overhead
#: tests. Adding an instrument = adding a row here — a new surface
#: cannot ship ungated, and the five historical ad-hoc gate lines
#: collapsed into this table.
GATE_MEASURES: tuple = (
    ("obs_overhead", measure),
    ("obs_trace_overhead", measure_trace),
    ("obs_journal_overhead", measure_journal),
    ("obs_health_overhead", measure_health),
    ("obs_correlate_overhead", measure_correlate),
    ("obs_latency_overhead", measure_latency),
    ("obs_predict_overhead", measure_predict),
    ("obs_fleet_overhead", measure_fleet),
)

#: the shared acceptance bar: each surface's projected per-tick cost
#: must stay under this fraction of the cadence budget
GATE_BUDGET_FRAC = 0.01
