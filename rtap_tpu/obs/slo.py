"""Operator-declared latency SLOs: burn-rate evaluation + budget gauges.

``serve --slo detect=2s@p99`` declares a contract — "99% of alerts must
be delivered within 2 s of their row's source timestamp" — and this
module defends it the way SRE practice defends error budgets
(docs/SLO.md is the runbook):

- every observation (a per-alert detect latency, a per-tick host
  latency) is judged good/bad against the target,
- bad-fraction is tracked over a FAST and a SLOW rolling tick window,
  and the **burn rate** (bad fraction / error budget fraction) over
  both must exceed their thresholds simultaneously before anything
  pages — the multi-window AND that kills both flavors of false alarm
  (a brief spike trips fast-only; a slow drift trips slow-only),
- the page is an **edge-triggered** ``slo_burn`` event on the alert
  stream (one line per episode, with hysteresis: re-arm only after both
  burn rates fall below ``rearm_frac`` of their thresholds), plus a
  flight-recorder postmortem dump so the waterfall that caused the burn
  is captured,
- cumulative budget exhaustion (``slo_budget_exhausted``) fires once
  when the run's total bad fraction overdraws the budget.

Specs parse from the operator grammar ``name=<target><unit>@p<q>``
(``detect=2s@p99``, ``tick=500ms@p95``); malformed specs raise
``ValueError`` with the exact complaint — the serve CLI turns that into
a usage error before any listener starts.
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass

import numpy as np

from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry

__all__ = ["SloSpec", "SloTracker", "parse_slo", "tick_slo_pair",
           "SLO_STAGES"]

#: stages an SLO may target — the LatencyTracker's sketch vocabulary
#: minus the raw per-phase internals nobody contracts on
SLO_STAGES = ("detect", "tick", "ingest", "dispatch", "collect", "emit")

_SPEC = re.compile(
    r"^(?P<name>[a-z_]+)=(?P<target>\d+(?:\.\d+)?)(?P<unit>ms|s)"
    r"@p(?P<q>\d+(?:\.\d+)?)$")


@dataclass(frozen=True)
class SloSpec:
    name: str  # the stage the SLO contracts on (SLO_STAGES)
    target_s: float  # latency objective in seconds
    quantile: float  # 0 < q < 1 (p99 -> 0.99); budget = 1 - q

    @property
    def budget_frac(self) -> float:
        return 1.0 - self.quantile

    def label(self) -> str:
        from rtap_tpu.obs.latency import qlabel

        t = self.target_s
        ts = f"{t * 1e3:g}ms" if t < 1.0 else f"{t:g}s"
        return f"{self.name}={ts}@{qlabel(self.quantile)}"


def parse_slo(spec: str) -> SloSpec:
    """``detect=2s@p99`` -> SloSpec. Raises ValueError on anything else,
    with a message naming the exact problem (the CLI's usage error)."""
    m = _SPEC.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad SLO spec {spec!r}: expected NAME=<target><ms|s>@p<q>, "
            "e.g. detect=2s@p99 or tick=500ms@p95")
    name = m.group("name")
    if name not in SLO_STAGES:
        raise ValueError(
            f"bad SLO spec {spec!r}: unknown stage {name!r} "
            f"(one of {', '.join(SLO_STAGES)})")
    target = float(m.group("target"))
    if m.group("unit") == "ms":
        target /= 1e3
    if target <= 0:
        raise ValueError(f"bad SLO spec {spec!r}: target must be > 0")
    q = float(m.group("q")) / 100.0
    if not (0.0 < q < 1.0):
        raise ValueError(
            f"bad SLO spec {spec!r}: quantile must be in (0, 100) "
            "exclusive — p100 has no error budget to burn")
    return SloSpec(name=name, target_s=target, quantile=q)


def tick_slo_pair(cadence_s: float, spec: str | None = None):
    """A LatencyTracker + SloTracker armed with a per-tick host-latency
    SLO — THE seeded-soak shape (crash/failover children): synthetic
    feed epochs rule out the wall-anchored detect SLO (docs/SLO.md
    clock contract), so those soaks contract on the tick stage instead.
    Default spec ``tick=<cadence>s@p99``; one helper so the soaks can
    never drift apart on the default/format logic."""
    from rtap_tpu.obs.latency import LatencyTracker

    # :.6f, not str(): a 1e-05-style float repr would fail the grammar
    spec = spec or f"tick={cadence_s:.6f}s@p99"
    latency = LatencyTracker(cadence_s=cadence_s)
    slo = SloTracker([parse_slo(spec)], cadence_s=cadence_s,
                     quantile_source=latency.quantile)
    return latency, slo


class _SloState:
    """One spec's rolling windows + burn state (loop-thread only)."""

    __slots__ = ("spec", "bad_ring", "total_ring", "idx", "filled",
                 "cur_bad", "cur_total", "cum_bad", "cum_total",
                 "burning", "burn_events", "exhausted", "recoveries")

    def __init__(self, spec: SloSpec, slow_window: int):
        self.spec = spec
        self.bad_ring = np.zeros(slow_window, np.int64)
        self.total_ring = np.zeros(slow_window, np.int64)
        self.idx = 0
        self.filled = 0
        self.cur_bad = 0  # accumulating since the last on_tick
        self.cur_total = 0
        self.cum_bad = 0
        self.cum_total = 0
        self.burning = False
        self.burn_events = 0
        self.exhausted = False
        self.recoveries = 0


class SloTracker:
    """Evaluates declared SLOs per tick; emits edge-triggered events.

    ``sink``/``flight`` follow the degradation-controller wiring
    contract (service/loop.py attaches ``AlertWriter.emit_event`` and
    the flight recorder); ``quantile_source`` is
    ``LatencyTracker.quantile`` so the verdict can report the observed
    quantile next to the target. Fast/slow windows are tick counts —
    at the standard 1 s cadence the defaults (60 / 600) are 1 min /
    10 min, scaled down from the SRE-book hours because a serve run is
    minutes-to-hours, not weeks.
    """

    def __init__(self, specs, cadence_s: float = 1.0,
                 fast_window: int = 60, slow_window: int = 600,
                 fast_burn: float = 14.0, slow_burn: float = 6.0,
                 rearm_frac: float = 0.5,
                 registry: TelemetryRegistry | None = None,
                 sink=None, flight=None, quantile_source=None):
        specs = list(specs)
        if not specs:
            raise ValueError("SloTracker needs at least one SloSpec")
        if not (1 <= fast_window <= slow_window):
            raise ValueError(
                f"need 1 <= fast_window <= slow_window; got "
                f"{fast_window}/{slow_window}")
        if fast_burn <= 0 or slow_burn <= 0:
            raise ValueError("burn thresholds must be > 0")
        if not (0.0 < rearm_frac < 1.0):
            raise ValueError(
                f"rearm_frac must be in (0, 1); got {rearm_frac}")
        seen: set[str] = set()
        for s in specs:
            if s.name in seen:
                raise ValueError(f"duplicate SLO for stage {s.name!r}")
            seen.add(s.name)
        self.specs = specs
        self.cadence_s = float(cadence_s)
        self.fast_window = int(fast_window)
        self.slow_window = int(slow_window)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.rearm_frac = float(rearm_frac)
        self.sink = sink
        self.flight = flight
        self.quantile_source = quantile_source
        self._states = {s.name: _SloState(s, self.slow_window)
                        for s in specs}
        reg = registry or get_registry()
        self._obs_events = {
            kind: reg.counter(
                "rtap_obs_slo_events_total",
                "SLO guardrail events by kind (edge-triggered; each also "
                "writes one JSONL line on the alert stream)", event=kind)
            for kind in ("slo_burn", "slo_recovered",
                         "slo_budget_exhausted")
        }
        self._obs_bad = {
            s.name: reg.counter(
                "rtap_obs_slo_bad_samples_total",
                "observations that violated their SLO target",
                slo=s.name)
            for s in specs
        }
        self._obs_burn_fast = {
            s.name: reg.gauge(
                "rtap_obs_slo_burn_rate",
                "error-budget burn rate (window bad fraction / budget "
                "fraction); 1.0 = burning exactly at budget",
                slo=s.name, window="fast")
            for s in specs
        }
        self._obs_burn_slow = {
            s.name: reg.gauge(
                "rtap_obs_slo_burn_rate",
                "error-budget burn rate (window bad fraction / budget "
                "fraction); 1.0 = burning exactly at budget",
                slo=s.name, window="slow")
            for s in specs
        }
        self._obs_budget = {
            s.name: reg.gauge(
                "rtap_obs_slo_error_budget_remaining",
                "fraction of the run's error budget left (1 = untouched, "
                "0 = spent, negative = overdrawn)", slo=s.name)
            for s in specs
        }

    # ------------------------------------------------------------ feed --
    def observe(self, stage: str, value_s: float) -> None:
        """Judge one observation against the stage's SLO (no-op for
        stages without one — callers need not know what was declared)."""
        st = self._states.get(stage)
        if st is None:
            return
        st.cur_total += 1
        if value_s > st.spec.target_s:
            st.cur_bad += 1

    def observe_many(self, stage: str, values_s: np.ndarray) -> None:
        st = self._states.get(stage)
        if st is None or values_s.size == 0:
            return
        st.cur_total += int(values_s.size)
        st.cur_bad += int((values_s > st.spec.target_s).sum())

    # ------------------------------------------------------------ tick --
    def _window_frac(self, st: _SloState, window: int) -> float:
        n = min(st.filled, window)
        if n == 0:
            return 0.0
        # the ring index points at the NEXT write slot; the last n
        # entries are the window
        sel = (st.idx - 1 - np.arange(n)) % self.slow_window
        total = int(st.total_ring[sel].sum())
        if total == 0:
            return 0.0
        return int(st.bad_ring[sel].sum()) / total

    def _event(self, kind: str, tick: int, st: _SloState,
               **fields) -> None:
        self._obs_events[kind].inc()
        ev = {"event": kind, "tick": int(tick),
              "slo": st.spec.label(), "stage": st.spec.name, **fields}
        if self.flight is not None:
            self.flight.record_event(ev)
        if self.sink is not None:
            self.sink(ev)

    def on_tick(self, tick: int) -> None:
        """Close the tick's counts into the rings; evaluate burn rates;
        raise/clear edge-triggered events (loop thread, once per tick)."""
        for st in self._states.values():
            if st.cur_bad:
                self._obs_bad[st.spec.name].inc(st.cur_bad)
            st.bad_ring[st.idx] = st.cur_bad
            st.total_ring[st.idx] = st.cur_total
            st.cum_bad += st.cur_bad
            st.cum_total += st.cur_total
            st.cur_bad = st.cur_total = 0
            st.idx = (st.idx + 1) % self.slow_window
            st.filled = min(st.filled + 1, self.slow_window)
            budget = st.spec.budget_frac
            fast = self._window_frac(st, self.fast_window) / budget
            slow = self._window_frac(st, self.slow_window) / budget
            self._obs_burn_fast[st.spec.name].set(round(fast, 4))
            self._obs_burn_slow[st.spec.name].set(round(slow, 4))
            remaining = 1.0 - (
                (st.cum_bad / st.cum_total) / budget if st.cum_total
                else 0.0)
            self._obs_budget[st.spec.name].set(round(remaining, 4))
            # warm-up gate: until the FAST window has filled, a couple
            # of bad first ticks read as burn rates of 10+ over a
            # two-tick "window" — a startup transient, not an episode.
            # Pages (and the exhaustion edge) wait for a full fast
            # window of history; the gauges above publish regardless.
            if st.filled < self.fast_window:
                continue
            # effective thresholds are clamped to what the declared
            # quantile can REACH: burn tops out at 1/budget (bad_frac
            # = 1), so a p90 SLO (max burn 10) against the default
            # fast threshold 14 could never page — clamp to 90%/50%
            # of the ceiling so a total violation always does
            fast_thr = min(self.fast_burn, 0.9 / budget)
            slow_thr = min(self.slow_burn, 0.5 / budget)
            if not st.burning:
                if fast >= fast_thr and slow >= slow_thr:
                    st.burning = True
                    st.burn_events += 1
                    self._event(
                        "slo_burn", tick, st,
                        burn_fast=round(fast, 2), burn_slow=round(slow, 2),
                        target_s=st.spec.target_s,
                        quantile=st.spec.quantile,
                        budget_remaining=round(remaining, 4))
                    if self.flight is not None:
                        # the fast burn is the black-box moment: capture
                        # the waterfall window that caused it
                        self.flight.request_dump("slo_burn", tick)
            else:
                if fast < self.rearm_frac * fast_thr and \
                        slow < self.rearm_frac * slow_thr:
                    st.burning = False
                    st.recoveries += 1
                    self._event("slo_recovered", tick,
                                st, burn_fast=round(fast, 2),
                                burn_slow=round(slow, 2))
            if not st.exhausted and st.cum_total and remaining <= 0.0:
                st.exhausted = True
                self._event(
                    "slo_budget_exhausted", tick, st,
                    bad=int(st.cum_bad), total=int(st.cum_total),
                    budget_frac=budget)
            elif st.exhausted and remaining > 0.1:
                st.exhausted = False  # re-arm well clear of the edge

    # --------------------------------------------------------- consume --
    def _verdict_one(self, st: _SloState) -> dict:
        spec = st.spec
        budget = spec.budget_frac
        bad_frac = (st.cum_bad / st.cum_total) if st.cum_total else 0.0
        observed_q = None
        if self.quantile_source is not None:
            observed_q = self.quantile_source(
                spec.name, spec.quantile, "total")
        # the contract: the declared quantile of observations met the
        # target — equivalently, the bad fraction stayed within budget.
        # Zero observations is NO DATA (met=None), not a pass or a
        # fail: a detect SLO on a run that never alerted proves nothing
        # either way, and a soak keying on met==False must not page
        met = (bad_frac <= budget) if st.cum_total else None
        return {
            "slo": spec.label(),
            "stage": spec.name,
            "target_s": spec.target_s,
            "quantile": spec.quantile,
            "met": met,
            "samples": int(st.cum_total),
            "bad": int(st.cum_bad),
            "bad_frac": round(bad_frac, 6),
            "budget_frac": round(budget, 6),
            "budget_remaining": round(
                1.0 - bad_frac / budget if st.cum_total else 1.0, 4),
            "observed_quantile_s": round(observed_q, 6)
            if observed_q is not None else None,
            "burn_events": st.burn_events,
            "recoveries": st.recoveries,
            "burning": st.burning,
        }

    def verdict(self) -> dict:
        """The run's SLO verdict — embedded in loop stats and every soak
        report: per-SLO met/bad-frac/budget plus an overall flag."""
        per = [self._verdict_one(st) for st in self._states.values()]
        return {
            # overall: no SLO is provably violated (no-data SLOs read
            # met=null individually and do not fail the run)
            "met": all(v["met"] is not False for v in per),
            "slos": per,
        }

    def snapshot(self) -> dict:
        """The ``GET /slo`` body: the live verdict plus window config."""
        return {
            "ts": time.time(),
            "fast_window_ticks": self.fast_window,
            "slow_window_ticks": self.slow_window,
            "fast_burn_threshold": self.fast_burn,
            "slow_burn_threshold": self.slow_burn,
            **self.verdict(),
        }

    def _window_sums(self, st: _SloState, window: int) -> tuple[int, int]:
        n = min(st.filled, window)
        if n == 0:
            return 0, 0
        sel = (st.idx - 1 - np.arange(n)) % self.slow_window
        return int(st.bad_ring[sel].sum()), int(st.total_ring[sel].sum())

    def fleet_state(self) -> list[dict]:
        """Per-SLO mergeable counts (the fleet push payload, ISSUE 19):
        raw bad/total sums for the fast/slow windows and the run, NOT
        fractions — the aggregator re-derives fleet burn rates from
        summed counts, the same anti-max-of-p99s discipline the merged
        sketches follow. Window lengths ride along so the aggregator can
        refuse to pool incomparable windows."""
        out = []
        for st in self._states.values():
            fast_bad, fast_total = self._window_sums(st, self.fast_window)
            slow_bad, slow_total = self._window_sums(st, self.slow_window)
            out.append({
                "stage": st.spec.name,
                "target_s": st.spec.target_s,
                "quantile": st.spec.quantile,
                "fast_window_ticks": self.fast_window,
                "slow_window_ticks": self.slow_window,
                "fast_bad": fast_bad, "fast_total": fast_total,
                "slow_bad": slow_bad, "slow_total": slow_total,
                "cum_bad": int(st.cum_bad),
                "cum_total": int(st.cum_total),
                "burning": st.burning,
                "burn_events": st.burn_events,
            })
        return out
