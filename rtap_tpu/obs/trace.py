"""Per-tick tracing: a near-zero-overhead host span recorder.

The obs registry (obs/metrics.py) answers "how much / how often"; this
module answers "what was happening *around* tick 48120": every loop phase
and per-group dispatch/collect becomes a SPAN (start + duration, tagged
with its tick index — the trace correlation id), and every watchdog /
resilience event becomes an INSTANT on the same timeline, so a
``group_quarantined`` mark lands visually inside the phase span that
raised it. Export is Chrome trace-event JSON (:meth:`chrome_trace`),
loadable directly in ui.perfetto.dev — via ``serve --trace-out FILE`` or
``GET /trace?last=N`` on the obs HTTP server (obs/expo.py).

Design constraints (same bar as the metrics seam — ≤ 1% of the tick
budget, obs/selfbench.py measures it):

- **No locks on the hot path.** Every writer thread owns a private ring
  shard keyed by ``threading.get_ident()`` — the metrics.py cell-sharding
  trick applied to span records. The loop thread and the dispatch-pool
  threads never touch each other's shards; export merges and sorts (cold
  path only).
- **Preallocated, strictly bounded memory.** Each shard is ONE numpy
  structured array of ``capacity`` records (:data:`REC_DTYPE`, 33 bytes
  each) plus a parallel instant-payload ring whose entries are truncated
  to ``max_arg_bytes``. Appending past capacity overwrites the oldest
  record and counts it in :attr:`dropped` — the recorder can run for an
  unbounded soak without growing.
- **Append is a handful of scalar stores.** One interned-name lookup
  (lock-free dict hit after the first use of a name), one structured-row
  tuple store, one integer increment. No allocation after a (thread,
  name) pair's first record.

Span names come from a small vocabulary (the six loop phases, "tick",
event kinds); the intern table is bounded at ``max_names`` and overflow
maps to ``"<other>"`` so a pathological caller cannot grow host memory
through the name channel.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np

__all__ = ["TraceRecorder", "REC_DTYPE"]

#: one trace record: interned name id, kind (0 span / 1 instant), tick
#: correlation id, start offset vs the recorder epoch (perf_counter
#: seconds), duration (0 for instants), group id (-1 = the loop track)
REC_DTYPE = np.dtype([
    ("name", np.int32),
    ("kind", np.int8),
    ("tick", np.int64),
    ("t0", np.float64),
    ("dur", np.float64),
    ("group", np.int32),
])

_KIND_SPAN = 0
_KIND_INSTANT = 1


class _Shard:
    """One writer thread's private ring (no cross-thread writes)."""

    __slots__ = ("recs", "aux", "n")

    def __init__(self, capacity: int):
        self.recs = np.zeros(capacity, REC_DTYPE)
        self.aux: list = [None] * capacity  # instant payloads (json str)
        self.n = 0  # total appended; ring index = n % capacity


class TraceRecorder:
    """Lock-free bounded span/instant ring with Chrome trace-event export.

    ``capacity`` is PER WRITER THREAD (the loop thread plus each dispatch
    pool worker gets its own ring); total memory is
    ``n_threads * capacity * (REC_DTYPE.itemsize + max_arg_bytes)`` worst
    case, asserted by tests/unit/test_trace.py.
    """

    def __init__(self, capacity: int = 65536, max_names: int = 1024,
                 max_arg_bytes: int = 256,
                 process_name: str | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1; got {capacity}")
        self.capacity = int(capacity)
        self.max_names = int(max_names)
        self.max_arg_bytes = int(max_arg_bytes)
        #: Perfetto process label (fleet stitching keys member traces by
        #: it); settable after construction — serve learns its role late
        self.process_name = process_name
        # perf_counter is the span clock (monotonic, sub-us); the unix
        # anchor lets a reader align the trace with alert-line timestamps
        self.epoch_perf = time.perf_counter()
        self.epoch_unix = time.time()
        self._shards: dict[int, _Shard] = {}
        self._names: dict[str, int] = {"<other>": 0}
        self._names_rev: list[str] = ["<other>"]
        self._names_lock = threading.Lock()

    # ------------------------------------------------------------ write --
    def _shard(self) -> _Shard:
        tid = threading.get_ident()
        shard = self._shards.get(tid)
        if shard is None:
            shard = self._shards.setdefault(tid, _Shard(self.capacity))
        return shard

    def _name_id(self, name: str) -> int:
        nid = self._names.get(name)
        if nid is not None:
            return nid
        with self._names_lock:
            nid = self._names.get(name)
            if nid is None:
                if len(self._names_rev) >= self.max_names:
                    return 0  # bounded vocabulary: overflow -> "<other>"
                nid = len(self._names_rev)
                self._names_rev.append(name)
                self._names[name] = nid
        return nid

    def add_span(self, name: str, tick: int, t0: float, dur: float,
                 group: int = -1) -> None:
        """Record one completed span. `t0` is a ``time.perf_counter()``
        reading (the caller already holds one from its own phase
        accounting — re-reading the clock here would double the cost)."""
        shard = self._shard()
        i = shard.n % self.capacity
        shard.recs[i] = (self._name_id(name), _KIND_SPAN, tick,
                         t0 - self.epoch_perf, dur, group)
        shard.aux[i] = None
        shard.n += 1

    def add_instant(self, name: str, tick: int, fields: dict | None = None,
                    group: int = -1) -> None:
        """Record one instant event (watchdog/resilience marks). `fields`
        is serialized now, truncated to `max_arg_bytes` — bounded memory
        beats a perfectly preserved payload (the full event also rides
        the alert JSONL stream)."""
        shard = self._shard()
        i = shard.n % self.capacity
        shard.recs[i] = (self._name_id(name), _KIND_INSTANT, tick,
                         time.perf_counter() - self.epoch_perf, 0.0, group)
        aux = None
        if fields:
            try:
                aux = json.dumps(fields)[: self.max_arg_bytes]
            except (TypeError, ValueError):
                aux = repr(fields)[: self.max_arg_bytes]
        shard.aux[i] = aux
        shard.n += 1

    # ------------------------------------------------------------- read --
    def _shard_list(self) -> list[_Shard]:
        for _ in range(8):
            try:
                return list(self._shards.values())
            except RuntimeError:  # dict resize under a brand-new writer
                continue
        return list(dict(self._shards).values())

    @property
    def total(self) -> int:
        """Records ever appended (spans + instants, including dropped)."""
        return sum(s.n for s in self._shard_list())

    @property
    def dropped(self) -> int:
        """Records overwritten by ring wrap-around."""
        return sum(max(0, s.n - self.capacity) for s in self._shard_list())

    def nbytes(self) -> int:
        """Current preallocated ring memory (structured arrays only; the
        instant-payload rings add at most capacity * max_arg_bytes per
        shard on top). The bound tests assert against this."""
        return sum(s.recs.nbytes for s in self._shard_list())

    def records(self, last_ticks: int | None = None) -> list[dict]:
        """Merged retained records as dicts, sorted by start time.

        `last_ticks=N` keeps only records whose tick is within the last N
        ticks seen across the whole recorder (instants and spans alike);
        records with tick < 0 (unticked) are always kept.
        """
        shards = [(s, min(s.n, self.capacity)) for s in self._shard_list()]
        lo = None
        if last_ticks is not None:
            # window at the numpy layer BEFORE building dicts: a live
            # /trace?last=10 poll must cost O(window), not O(full ring)
            # of GIL-holding dict construction under the serve loop
            hi = max((int(s.recs["tick"][:n].max())
                      for s, n in shards if n), default=None)
            if hi is None:
                return []
            lo = hi - int(last_ticks) + 1
        out = []
        for shard, n in shards:
            if lo is not None:
                ticks = shard.recs["tick"][:n]
                idx = np.nonzero((ticks >= lo) | (ticks < 0))[0]
            else:
                idx = range(n)
            for j in idx:
                r = shard.recs[j]
                rec = {
                    "name": self._names_rev[int(r["name"])],
                    "kind": "span" if r["kind"] == _KIND_SPAN else "instant",
                    "tick": int(r["tick"]),
                    "t0": float(r["t0"]),
                    "dur": float(r["dur"]),
                    "group": int(r["group"]),
                }
                if shard.aux[j] is not None:
                    rec["args_json"] = shard.aux[j]
                out.append(rec)
        out.sort(key=lambda r: r["t0"])
        return out

    def chrome_trace(self, last_ticks: int | None = None) -> dict:
        """The retained timeline as Chrome trace-event JSON (the object
        form: ``{"traceEvents": [...]}``), loadable in ui.perfetto.dev.

        Track layout: tid 0 is the loop thread (phase spans + tick spans
        + untargeted instants); each group `g` gets tid ``g + 1`` for its
        dispatch/collect child spans and group-targeted instants.
        Timestamps are microseconds since the recorder epoch. ``pid`` is
        the REAL process id and a ``process_name`` metadata event labels
        the track — two traces from a leader/standby pair drop onto one
        Perfetto timeline as distinct processes (the otherData epoch
        anchors are what scripts/fleet_trace.py aligns clocks with).
        """
        recs = self.records(last_ticks=last_ticks)
        pid = os.getpid()
        events: list[dict] = [{
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": self.process_name or f"rtap-{pid}"},
        }, {
            "ph": "M", "pid": pid, "tid": 0, "name": "thread_name",
            "args": {"name": "serve loop"},
        }]
        seen_groups: set[int] = set()
        for r in recs:
            g = r["group"]
            tid = 0 if g < 0 else g + 1
            if g >= 0 and g not in seen_groups:
                seen_groups.add(g)
                events.append({
                    "ph": "M", "pid": pid, "tid": tid,
                    "name": "thread_name",
                    "args": {"name": f"group{g}"},
                })
            args: dict = {"tick": r["tick"]}
            if g >= 0:
                args["group"] = g
            if "args_json" in r:
                try:
                    args.update(json.loads(r["args_json"]))
                except ValueError:
                    args["info"] = r["args_json"]
            ev = {
                "name": r["name"],
                "cat": "phase" if g < 0 else "group",
                "pid": pid,
                "tid": tid,
                "ts": round(r["t0"] * 1e6, 3),
                "args": args,
            }
            if r["kind"] == "span":
                ev["ph"] = "X"
                ev["dur"] = round(r["dur"] * 1e6, 3)
            else:
                ev["ph"] = "i"
                ev["s"] = "g"  # global scope: the mark spans all tracks
                ev["cat"] = "event"
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "pid": pid,
                "process_name": self.process_name or f"rtap-{pid}",
                "epoch_unix": self.epoch_unix,
                "epoch_perf": self.epoch_perf,
                "total_records": self.total,
                "dropped_records": self.dropped,
            },
        }
