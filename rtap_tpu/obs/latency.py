"""Detection-latency observability: quantile sketches + stage waterfalls.

The product claim is *real-time* anomaly prediction, and until now the
stack measured everything EXCEPT the product metric: the time from a
metric row's SOURCE timestamp to the alert line that names it. This
module is the measurement substrate (ISSUE 11):

- :class:`QuantileSketch` — a bounded, lock-free, log-bucketed sketch
  with **windowed** p50/p95/p99/p99.9 extraction, in the style of
  obs/metrics.py's Histogram (per-writer-thread shards, bisect over a
  plain-float edge list, in-place numpy int64 increments — O(log n)
  observe, allocation-free after a thread's first observe). Unlike the
  registry Histogram it keeps a rolling window (current + previous) next
  to the lifetime totals, so ``GET /latency`` answers "what is p99 NOW",
  not "since process start".
- :class:`LatencyTracker` — the per-tick stage-waterfall fold: source
  ts → ingest arrival / backfill release → dispatch → collect →
  alert-sink flush, one sketch per stage, plus first-class lag gauges
  (replication-ack lag, incident-close lag) polled from providers the
  CLI wires in. The end-to-end ``detect`` sketch is fed per ALERT by
  AlertWriter at sink-write time — wall clock minus the row's source
  timestamp, so pipeline depth, micro-chunk staleness and backfill hold
  all show up honestly. Zero extra device↔host fetches: every input is
  a host-side wall clock or a timestamp already riding the rows.

With the flag off nothing here is constructed and the serve path is
byte/bit-identical to a flagless run (tests/integration/
test_latency_serve.py pins it, the PR 6 health-flag discipline). Armed,
the hot-path cost is gated <= 1% of the tick budget next to the other
obs instruments (obs/selfbench.measure_latency, bench.py --obs-bench).

Clock contract: ``detect`` compares the host wall clock against the
row's source timestamp, so it is meaningful when producers stamp rows
with (approximately) synchronized wall clocks — the serve deployment
shape. Seeded soaks on a synthetic epoch (crash/failover) declare
``tick=...`` SLOs instead (docs/SLO.md).
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left

import numpy as np

from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry

__all__ = ["QuantileSketch", "LatencyTracker", "STAGES", "DEFAULT_QS"]

#: the per-tick waterfall stages, in pipeline order. ``ingest`` is the
#: source-ts -> loop-poll lag (wire transit + any backfill hold);
#: ``dispatch``/``collect``/``emit`` are the loop's own phase deltas;
#: ``tick`` is the whole host tick; ``detect`` is the per-alert e2e.
STAGES = ("ingest", "dispatch", "collect", "emit", "tick", "detect")

#: the standard extraction points (ISSUE 11 tentpole)
DEFAULT_QS = (0.5, 0.95, 0.99, 0.999)


def qlabel(q: float) -> str:
    """THE quantile label (0.99 -> "p99", 0.999 -> "p99.9") — one
    formatter shared by the sketch's JSON keys, the detect-quantile
    gauge labels, and SloSpec.label, so the snapshot path and the live
    routes can never disagree on a name."""
    return f"p{round(q * 100, 4):g}"


def _edges(lo: float, hi: float, per_decade: int) -> tuple[float, ...]:
    n = int(round(np.log10(hi / lo) * per_decade))
    e = lo * (10.0 ** (np.arange(n + 1) / per_decade))
    e[-1] = max(e[-1], hi)
    return tuple(float(x) for x in e)


class _SketchShard:
    """One writer thread's private window/total counts (no cross-thread
    writes; readers sum — the obs/metrics.py sharding idiom)."""

    __slots__ = ("cur", "prev", "total", "sum", "max")

    def __init__(self, n: int):
        self.cur = np.zeros(n, np.int64)
        self.prev = np.zeros(n, np.int64)
        self.total = np.zeros(n, np.int64)
        self.sum = 0.0
        self.max = 0.0


class QuantileSketch:
    """Bounded log-bucketed quantile sketch with a rolling window.

    Buckets are geometric (default 0.1 ms .. 100 s at ``per_decade=20``
    — a 12% ratio per bucket, so an interpolated quantile is within one
    bucket ratio of the exact order statistic; the fuzz test pins it
    against ``numpy.percentile``). Values below the range clamp into the
    first bucket, values at/above it into the overflow bucket (whose
    quantiles report the top edge — saturation, never a lie about
    resolution the sketch doesn't have). Negative inputs clamp to 0.

    ``observe`` is lock-free (per-thread shards); ``roll()`` — called by
    the single owner thread at window boundaries — retires the current
    window to ``prev``, so windowed extraction always covers between one
    and two windows of history (never a just-emptied array).
    """

    def __init__(self, lo: float = 1e-4, hi: float = 100.0,
                 per_decade: int = 20):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi; got lo={lo}, hi={hi}")
        if per_decade < 1:
            raise ValueError(f"per_decade must be >= 1; got {per_decade}")
        # geometry kept verbatim so state()/from_state() round-trips
        # rebuild bit-identical edge arrays (merge requires identity)
        self.lo = float(lo)
        self.hi = float(hi)
        self.per_decade = int(per_decade)
        self.edges = _edges(lo, hi, per_decade)
        self._edges_list = list(self.edges)
        self._edges_arr = np.asarray(self.edges)  # searchsorted target
        # (cached like _edges_list: observe_many sits on the per-alert
        # hot path and must not re-materialize the tuple per call)
        self._n = len(self.edges) + 1  # + overflow
        self._shards: dict[int, _SketchShard] = {}
        self.rolls = 0

    def _shard_list(self) -> list:
        """Point-in-time shard list, tolerating a brand-new writer
        thread's first observe resizing the dict mid-iteration (the
        obs/metrics.py retry idiom; read-only either way)."""
        for _ in range(8):
            try:
                return list(self._shards.values())
            except RuntimeError:
                continue
        return list(dict(self._shards).values())

    def observe(self, v: float) -> None:
        shard = self._shards.get(threading.get_ident())
        if shard is None:
            shard = self._shards.setdefault(
                threading.get_ident(), _SketchShard(self._n))
        if v < 0.0:
            v = 0.0
        i = bisect_left(self._edges_list, v)
        shard.cur[i] += 1
        shard.total[i] += 1
        shard.sum += v
        if v > shard.max:
            shard.max = v

    def observe_many(self, values) -> int:
        """Vectorized observe (the per-alert batch path); returns n."""
        values = np.maximum(np.asarray(values, np.float64).ravel(), 0.0)
        if values.size == 0:
            return 0
        shard = self._shards.get(threading.get_ident())
        if shard is None:
            shard = self._shards.setdefault(
                threading.get_ident(), _SketchShard(self._n))
        idx = np.searchsorted(self._edges_arr, values, side="left")
        np.add.at(shard.cur, idx, 1)
        np.add.at(shard.total, idx, 1)
        shard.sum += float(values.sum())
        m = float(values.max())
        if m > shard.max:
            shard.max = m
        return int(values.size)

    def roll(self) -> None:
        """Retire the current window (owner-thread call, once per window
        boundary). Writers racing the swap can at worst land one observe
        in the just-retired window — diagnostic tolerance, same as a
        scrape racing a write in obs/metrics.py."""
        self.rolls += 1
        for s in self._shard_list():
            s.prev[:] = s.cur
            s.cur[:] = 0

    def _merged(self, scope: str) -> np.ndarray:
        out = np.zeros(self._n, np.int64)
        for s in self._shard_list():
            if scope == "total":
                out += s.total
            else:  # window: last complete + current partial
                out += s.prev
                out += s.cur
        return out

    def count(self, scope: str = "window") -> int:
        return int(self._merged(scope).sum())

    def quantile(self, q: float, scope: str = "window") -> float | None:
        """Interpolated quantile over the scope's counts; None if empty."""
        counts = self._merged(scope)
        total = int(counts.sum())
        if total == 0:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= rank:
                if i >= len(self.edges):
                    return self.edges[-1]  # overflow saturates at hi
                hi_e = self.edges[i]
                lo_e = self.edges[i - 1] if i > 0 else 0.0
                frac = (rank - cum) / c
                if lo_e <= 0.0:
                    return hi_e * frac  # sub-resolution bucket: linear
                return float(lo_e * (hi_e / lo_e) ** frac)
            cum += c
        return self.edges[-1]

    def quantiles(self, qs=DEFAULT_QS, scope: str = "window") -> dict:
        return {qlabel(q): self.quantile(q, scope) for q in qs}

    def nbytes(self) -> int:
        """Preallocated counter memory (the bounded-memory pin: constant
        regardless of how many values were observed)."""
        return sum(s.cur.nbytes + s.prev.nbytes + s.total.nbytes
                   for s in self._shard_list())

    def summary(self, scope: str = "window") -> dict:
        out = {"count": self.count(scope),
               **{k: (round(v, 6) if v is not None else None)
                  for k, v in self.quantiles(scope=scope).items()}}
        if scope == "total":
            shards = self._shard_list()
            out["sum_s"] = round(sum(sh.sum for sh in shards), 6)
            out["max_s"] = round(
                max((sh.max for sh in shards), default=0.0), 6)
        return out

    # ------------------------------------------------- fleet merge core --
    def state(self) -> dict:
        """Lossless wire form (the fleet push payload, ISSUE 19): bucket
        geometry + the shard-summed count arrays for every scope. A
        sketch rebuilt by :meth:`from_state` answers every quantile/count
        query identically to this one — the counts ARE the sketch."""
        cur = np.zeros(self._n, np.int64)
        prev = np.zeros(self._n, np.int64)
        total = np.zeros(self._n, np.int64)
        sum_s = 0.0
        max_s = 0.0
        for s in self._shard_list():
            cur += s.cur
            prev += s.prev
            total += s.total
            sum_s += s.sum
            if s.max > max_s:
                max_s = s.max
        return {"v": 1, "lo": self.lo, "hi": self.hi,
                "per_decade": self.per_decade,
                "cur": cur.tolist(), "prev": prev.tolist(),
                "total": total.tolist(),
                "sum": sum_s, "max": max_s, "rolls": self.rolls}

    @classmethod
    def from_state(cls, wire: dict) -> "QuantileSketch":
        """Rebuild a sketch from :meth:`state` output (a plain-JSON wire
        payload, not a model state tree). Raises ValueError on
        geometry/count-length mismatch (a corrupt or skewed payload
        must never fold silently into a fleet quantile)."""
        sk = cls(lo=float(wire["lo"]), hi=float(wire["hi"]),
                 per_decade=int(wire["per_decade"]))
        cur = np.asarray(wire["cur"], np.int64)
        prev = np.asarray(wire["prev"], np.int64)
        total = np.asarray(wire["total"], np.int64)
        if not (cur.shape == prev.shape == total.shape == (sk._n,)):
            raise ValueError(
                f"sketch state count arrays have wrong length "
                f"(want {sk._n}, got {cur.shape}/{prev.shape}/"
                f"{total.shape})")
        shard = sk._shards.setdefault(threading.get_ident(),
                                      _SketchShard(sk._n))
        shard.cur[:] = cur
        shard.prev[:] = prev
        shard.total[:] = total
        shard.sum = float(wire.get("sum", 0.0))
        shard.max = float(wire.get("max", 0.0))
        sk.rolls = int(wire.get("rolls", 0))
        return sk

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other``'s counts into this sketch, losslessly, scope by
        scope (cur+cur, prev+prev, total+total, sum/max folded). Only
        sketches over IDENTICAL bucket edges merge — fleet p99s must come
        from summed counts over one geometry, never from resampling
        (which would silently re-introduce the max-of-p99s lie this
        exists to kill). Returns self for chaining."""
        if other.edges != self.edges:
            raise ValueError(
                f"cannot merge sketches with different bucket edges "
                f"(lo/hi/per_decade {self.lo}/{self.hi}/{self.per_decade}"
                f" vs {other.lo}/{other.hi}/{other.per_decade})")
        shard = self._shards.get(threading.get_ident())
        if shard is None:
            shard = self._shards.setdefault(
                threading.get_ident(), _SketchShard(self._n))
        for s in other._shard_list():
            shard.cur += s.cur
            shard.prev += s.prev
            shard.total += s.total
            shard.sum += s.sum
            if s.max > shard.max:
                shard.max = s.max
        return self


class LatencyTracker:
    """Per-tick stage-waterfall fold + the per-alert e2e detect sketch.

    ``record_tick`` (loop thread, once per tick) observes each stage's
    wall seconds into its sketch, keeps the latest waterfall for
    ``GET /latency`` / postmortem embedding, polls the lag providers,
    and rolls the windows every ``window_ticks``. ``observe_detect``
    (AlertWriter, at sink-write time) feeds the e2e sketch. Both run on
    the loop thread by the serve stack's emission contract; the sketch
    shards tolerate other writers anyway.
    """

    def __init__(self, window_ticks: int = 120, cadence_s: float = 1.0,
                 registry: TelemetryRegistry | None = None, slo=None):
        if window_ticks < 1:
            raise ValueError(
                f"window_ticks must be >= 1; got {window_ticks}")
        self.window_ticks = int(window_ticks)
        self.cadence_s = float(cadence_s)
        self.slo = slo  # optional obs.slo.SloTracker fed per observation
        self.sketches = {s: QuantileSketch() for s in STAGES}
        self.last_waterfall: dict | None = None
        self.ticks = 0
        self.detect_samples = 0
        #: name -> callable(tick, ts) -> float | None; polled once per
        #: tick into rtap_obs_latency_lag{lag=name} (repl ack lag,
        #: incident-close lag — the CLI wires them)
        self.lag_providers: dict = {}
        self.last_lags: dict = {}
        reg = registry or get_registry()
        self._obs_samples = reg.counter(
            "rtap_obs_latency_samples_total",
            "per-alert end-to-end detection-latency samples observed at "
            "alert-sink write time (wall clock minus row source ts)")
        self._obs_rolls = reg.counter(
            "rtap_obs_latency_window_rolls_total",
            "quantile-sketch window boundaries crossed "
            "(--latency-window ticks each)")
        self._obs_q = {
            q: reg.gauge(
                "rtap_obs_latency_detect_seconds",
                "windowed detection-latency quantiles (source ts -> "
                "alert-sink flush), updated at window rolls and run end",
                quantile=qlabel(q))
            for q in DEFAULT_QS
        }
        self._obs_lag = {}
        self._reg = reg

    # ------------------------------------------------------------ feed --
    def observe_detect(self, lag_s) -> None:
        """Per-alert e2e latency (scalar or vector of wall-minus-source
        seconds), observed by AlertWriter after the batch reached the
        sink. Also feeds any ``detect`` SLO."""
        n = self.sketches["detect"].observe_many(lag_s)
        if n == 0:
            return
        self.detect_samples += n
        self._obs_samples.inc(n)
        if self.slo is not None:
            self.slo.observe_many("detect", np.asarray(lag_s, np.float64))

    def record_tick(self, tick: int, ts: int, phase_deltas: dict,
                    elapsed_s: float, poll_wall: float | None = None,
                    source=None) -> None:
        """Fold one tick's stage facts (loop thread).

        ``poll_wall`` is the wall clock right after the source poll;
        ``ts`` the tick's (clamped) source timestamp. ``source`` is
        duck-probed for the binary-ingest arrival/backfill surfaces
        (``last_arrival_lag_s`` / ``last_release_hold_s``) — absent on
        JSONL/HTTP sources, absent means the stage is simply not in the
        waterfall."""
        sk = self.sketches
        slo = self.slo
        ingest_lag = None
        if poll_wall is not None:
            ingest_lag = max(0.0, float(poll_wall) - float(ts))
            sk["ingest"].observe(ingest_lag)
            if slo is not None:
                slo.observe("ingest", ingest_lag)
        for stage in ("dispatch", "collect", "emit"):
            d = float(phase_deltas.get(stage, 0.0))
            sk[stage].observe(d)
            if slo is not None:
                # every measured stage feeds its (possibly declared)
                # SLO — an operator contract on emit/dispatch latency
                # must judge, not sit inert (observe is a dict miss for
                # undeclared stages)
                slo.observe(stage, d)
        sk["tick"].observe(float(elapsed_s))
        if slo is not None:
            slo.observe("tick", float(elapsed_s))
        wf = {
            "tick": int(tick),
            "ts": int(ts),
            "ingest_lag_s": round(ingest_lag, 6)
            if ingest_lag is not None else None,
            "dispatch_s": round(float(phase_deltas.get("dispatch", 0.0)), 6),
            "collect_s": round(float(phase_deltas.get("collect", 0.0)), 6),
            "emit_s": round(float(phase_deltas.get("emit", 0.0)), 6),
            "tick_s": round(float(elapsed_s), 6),
        }
        arrival = getattr(source, "last_arrival_lag_s", None)
        if arrival is not None:
            wf["arrival_lag_s"] = round(float(arrival), 6)
        hold = getattr(source, "last_release_hold_s", None)
        if hold is not None:
            wf["backfill_hold_s"] = round(float(hold), 6)
        for name, provider in self.lag_providers.items():
            try:
                v = provider(tick, ts)
            except Exception:  # noqa: BLE001 — a lag probe must not
                v = None  # kill the tick it narrates
            if v is None:
                continue
            self.last_lags[name] = float(v)
            g = self._obs_lag.get(name)
            if g is None:
                g = self._obs_lag[name] = self._reg.gauge(
                    "rtap_obs_latency_lag",
                    "first-class pipeline lag gauges by kind "
                    "(repl_ack_ticks, incident_close_s, ...)", lag=name)
            g.set(float(v))
        if self.last_lags:
            wf["lags"] = dict(self.last_lags)
        self.last_waterfall = wf
        self.ticks += 1
        if self.ticks % self.window_ticks == 0:
            self._roll()

    def _roll(self) -> None:
        for sk in self.sketches.values():
            sk.roll()
        self._obs_rolls.inc()
        self._publish_gauges()

    def _publish_gauges(self) -> None:
        for q, g in self._obs_q.items():
            v = self.sketches["detect"].quantile(q)
            if v is not None:
                g.set(round(v, 6))

    # --------------------------------------------------------- consume --
    def quantile(self, stage: str, q: float,
                 scope: str = "window") -> float | None:
        """Stage quantile — the SLO verdict's observed-value source."""
        sk = self.sketches.get(stage)
        return None if sk is None else sk.quantile(q, scope)

    def snapshot(self) -> dict:
        """The ``GET /latency`` body: per-stage windowed + lifetime
        quantiles, the latest waterfall, and the lag gauges."""
        return {
            "ts": time.time(),
            "window_ticks": self.window_ticks,
            "ticks": self.ticks,
            "detect_samples": self.detect_samples,
            "stages": {
                name: {"window": sk.summary("window"),
                       "total": sk.summary("total")}
                for name, sk in self.sketches.items()
            },
            "waterfall": self.last_waterfall,
            "lags": dict(self.last_lags),
        }

    def sketch_states(self) -> dict:
        """Per-stage lossless sketch states (the fleet push payload) —
        the aggregator rebuilds and merges these so fleet quantiles are
        computed from pooled counts, not from per-member quantiles."""
        return {name: sk.state() for name, sk in self.sketches.items()}

    def stats(self) -> dict:
        """End-of-run block for the loop's stats dict (and the soak
        artifacts). Publishes the final quantile gauges so the exit
        snapshot carries fresh values."""
        self._publish_gauges()
        return {
            "window_ticks": self.window_ticks,
            "ticks": self.ticks,
            "detect_samples": self.detect_samples,
            "detect": self.sketches["detect"].summary("total"),
            "stages": {name: self.sketches[name].summary("total")
                       for name in STAGES if name != "detect"},
            "waterfall": self.last_waterfall,
            **({"lags": dict(self.last_lags)} if self.last_lags else {}),
        }
