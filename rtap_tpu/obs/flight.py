"""Black-box flight recorder: bounded per-tick history + postmortem bundles.

A crashed or degraded hour-long soak used to leave only a final metrics
snapshot behind; the flight recorder keeps the last ``n_ticks`` ticks of
evidence — per-tick latency, per-phase wall-second deltas, per-group
scored digests, deadline verdicts, and the recent structured events — in
STRICTLY BOUNDED preallocated rings, and dumps an atomic postmortem
bundle when something goes wrong:

- ``group_quarantined`` (a dispatch/collect fault isolated a group),
- a degradation-level change (the load-shedding ladder moved),
- a missed-tick burst (``miss_burst`` consecutive deadline misses),
- an unhandled exception escaping ``serve`` (the CLI's excepthook path),
- or on demand (``GET /postmortem`` on the obs HTTP server, or a direct
  :meth:`dump` call).

A bundle is one directory, written to a temp sibling and ``os.rename``d
into place (a reader never sees a half-written bundle):

- ``trace.json``   — the span recorder's Chrome trace-event JSON over the
  flight window (loadable in ui.perfetto.dev; docs/POSTMORTEM.md),
- ``events.jsonl`` — the retained structured event lines, in order,
- ``summary.json`` — reason + tick, window stats (per-phase mean/max,
  misses, per-group scored totals), the telemetry-registry summary, and
  the caller-supplied config/info block.

``scripts/postmortem.py`` pretty-prints a bundle; :func:`validate_bundle`
is the machine check (used by the chaos soak and the tier-1 tests).
Dumps are throttled (``min_dump_gap_ticks`` per reason, ``max_bundles``
per run) so a quarantine storm cannot fill the disk — except the
``unhandled_exception`` crash dump, which is always admitted (the black
box's whole point is evidence of the death). Bundle names carry a
per-run tag (start time + pid), so re-runs into the same directory
never collide with a prior run's bundles.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

import numpy as np

from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry

__all__ = ["FlightRecorder", "validate_bundle"]

_BUNDLE_FILES = ("summary.json", "events.jsonl")


class FlightRecorder:
    """Bounded ring of the last N ticks + auto-dumped postmortem bundles.

    ``record_tick`` is the only hot-path call (one per tick): a handful of
    numpy scalar stores into preallocated rings, lazily sized to the
    fleet's group count on the first tick. Everything else (event capture,
    dumping) is rare by construction.
    """

    def __init__(self, trace=None, n_ticks: int = 240,
                 out_dir: str | None = None,
                 registry: TelemetryRegistry | None = None,
                 n_events: int = 512, max_event_bytes: int = 1024,
                 miss_burst: int = 5, min_dump_gap_ticks: int = 120,
                 max_bundles: int = 16, info: dict | None = None,
                 health_provider=None, latency_provider=None,
                 predict_provider=None):
        if n_ticks < 1:
            raise ValueError(f"n_ticks must be >= 1; got {n_ticks}")
        if miss_burst < 1:
            raise ValueError(f"miss_burst must be >= 1; got {miss_burst}")
        self.trace = trace
        self.n_ticks = int(n_ticks)
        self.out_dir = out_dir
        self.registry = registry or get_registry()
        self.miss_burst = int(miss_burst)
        self.min_dump_gap_ticks = int(min_dump_gap_ticks)
        self.max_bundles = int(max_bundles)
        self.max_event_bytes = int(max_event_bytes)
        self.info = dict(info or {})
        # optional model-health snapshot source (obs/health.py ISSUE 6):
        # a callable returning a JSON-able dict, embedded in every
        # bundle's summary.json so triage gets model state, not just
        # timing. live_loop wires the HealthTracker's snapshot in.
        self.health_provider = health_provider
        # optional detection-latency source (obs/latency.py ISSUE 11):
        # same contract — the latest stage waterfall + windowed
        # quantiles land in every bundle's summary, so an slo_burn (or
        # any other) postmortem names the stage that ate the budget
        self.latency_provider = latency_provider
        # optional predictive-horizon scorecard source (rtap_tpu/predict/
        # ISSUE 16): same contract — the divergence trajectories and
        # open blast windows land in every bundle's summary, so a
        # precursor postmortem shows what the predictor saw
        self.predict_provider = predict_provider
        # tick rings (preallocated; the scored ring is sized on first use
        # because the group count is the loop's to know)
        self._tick = np.full(self.n_ticks, -1, np.int64)
        self._elapsed = np.zeros(self.n_ticks, np.float64)
        self._missed = np.zeros(self.n_ticks, bool)
        self._phases: np.ndarray | None = None  # [n_ticks, n_phases] f64
        self._phase_names: tuple[str, ...] = ()
        self._scored: np.ndarray | None = None  # [n_ticks, n_groups] i64
        self._n = 0
        self._last_tick = -1
        self._miss_run = 0
        # bounded event ring: pre-serialized, truncated lines
        self._events: deque[str] = deque(maxlen=int(n_events))
        self._events_by_kind: dict[str, int] = {}
        self._events_total = 0
        # per-run tag in every bundle name: a re-run pointed at the same
        # --postmortem-dir (hw_session steps hardcode theirs; chaos
        # workdirs are reusable) must never collide with a prior run's
        # bundle — os.rename onto an existing dir fails ENOTEMPTY and
        # would silently drop the NEW incident's postmortem
        self._run_tag = f"{int(time.time())}-{os.getpid()}"
        # dump state. The lock serializes dump() only — the loop thread's
        # flush_pending and the obs server's /postmortem handler may race,
        # and both derive the bundle name/tmp dir from len(self.bundles)
        self._dump_lock = threading.Lock()
        self._pending: list[tuple[str, int]] = []
        self._last_dump_tick: dict[str, int] = {}
        self.bundles: list[str] = []
        self.dumps_skipped = 0
        self._obs_bundles: dict = {}
        self._obs_last_tick = self.registry.gauge(
            "rtap_obs_postmortem_last_tick",
            "tick index of the most recent postmortem bundle dump")
        self._obs_skipped = self.registry.counter(
            "rtap_obs_postmortem_dump_skipped_total",
            "postmortem dumps suppressed by throttling (per-reason gap or "
            "the per-run bundle cap)")
        self._obs_dump_seconds = self.registry.histogram(
            "rtap_obs_postmortem_dump_seconds",
            "wall seconds per postmortem bundle dump (trace export + "
            "writes + atomic rename)")

    # ----------------------------------------------------------- record --
    def record_tick(self, tick: int, elapsed_s: float,
                    phase_seconds: dict[str, float],
                    scored_by_group, missed: bool) -> None:
        """One tick's facts into the ring; also advances the missed-tick
        burst detector (which queues a dump, never writes inline)."""
        if self._phases is None:
            self._phase_names = tuple(phase_seconds)
            self._phases = np.zeros((self.n_ticks, len(self._phase_names)),
                                    np.float64)
        if self._scored is None:
            self._scored = np.zeros((self.n_ticks, len(scored_by_group)),
                                    np.int64)
        i = self._n % self.n_ticks
        self._tick[i] = tick
        self._elapsed[i] = elapsed_s
        self._missed[i] = missed
        for j, p in enumerate(self._phase_names):
            self._phases[i, j] = phase_seconds.get(p, 0.0)
        ng = min(len(scored_by_group), self._scored.shape[1])
        self._scored[i, :ng] = scored_by_group[:ng]
        self._n += 1
        self._last_tick = int(tick)
        if missed:
            self._miss_run += 1
            if self._miss_run == self.miss_burst:
                self.request_dump("missed_tick_burst", tick)
        else:
            self._miss_run = 0

    def record_event(self, event: dict) -> None:
        """Capture one structured event line (same dicts that ride the
        alert JSONL stream). Bounded: the ring keeps the last `n_events`,
        each truncated to `max_event_bytes`."""
        kind = str(event.get("event", "?"))
        self._events_by_kind[kind] = self._events_by_kind.get(kind, 0) + 1
        self._events_total += 1
        try:
            line = json.dumps(event)
        except (TypeError, ValueError):
            line = json.dumps({"event": kind, "repr": repr(event)[:256]})
        self._events.append(line[: self.max_event_bytes])

    def nbytes(self) -> int:
        """Preallocated tick-ring memory (the bound the unit test pins;
        the event ring adds at most n_events * max_event_bytes on top)."""
        n = self._tick.nbytes + self._elapsed.nbytes + self._missed.nbytes
        if self._phases is not None:
            n += self._phases.nbytes
        if self._scored is not None:
            n += self._scored.nbytes
        return n

    # ------------------------------------------------------------- dump --
    def request_dump(self, reason: str, tick: int) -> None:
        """Queue a dump; the loop drains the queue at tick end
        (:meth:`flush_pending`) so bundle writes never land inside a
        phase's accounting."""
        self._pending.append((reason, int(tick)))

    def flush_pending(self) -> list[str]:
        """Write every queued dump (throttled); returns bundle paths."""
        paths = []
        pending, self._pending = self._pending, []
        for reason, tick in pending:
            p = self.dump(reason, tick)
            if p is not None:
                paths.append(p)
        return paths

    def _allowed(self, reason: str, tick: int) -> bool:
        if self.out_dir is None:
            return False
        if reason == "unhandled_exception":
            # the crash black box is the whole point: a soak that spent
            # its bundle budget on quarantine churn must STILL leave its
            # dying evidence behind — exempt from cap and gap alike
            return True
        if len(self.bundles) >= self.max_bundles:
            return False
        last = self._last_dump_tick.get(reason)
        return last is None or tick - last >= self.min_dump_gap_ticks

    def _window(self) -> np.ndarray:
        """Indices of the retained ring rows, oldest first."""
        n = min(self._n, self.n_ticks)
        if n == 0:
            return np.empty(0, np.int64)
        start = self._n - n
        return (start + np.arange(n)) % self.n_ticks

    def summary(self, reason: str = "snapshot",
                tick: int | None = None) -> dict:
        """The bundle's summary.json content (also the /postmortem and
        postmortem.py surface — one schema everywhere)."""
        idx = self._window()
        out: dict = {
            "reason": reason,
            "tick": int(self._last_tick if tick is None else tick),
            "created_unix": time.time(),
            "bundle_seq": len(self.bundles),
            "info": self.info,
            "ticks": {
                "count": int(idx.size),
                "first": int(self._tick[idx[0]]) if idx.size else None,
                "last": int(self._tick[idx[-1]]) if idx.size else None,
                "missed": int(self._missed[idx].sum()) if idx.size else 0,
                "miss_run": self._miss_run,
            },
            "events": {
                "total_seen": self._events_total,
                "retained": len(self._events),
                "by_kind": dict(sorted(self._events_by_kind.items())),
            },
            "trace": None if self.trace is None else {
                "records": self.trace.total,
                "dropped": self.trace.dropped,
            },
        }
        if idx.size:
            el = self._elapsed[idx]
            out["tick_ms"] = {"mean": round(float(el.mean()) * 1e3, 3),
                              "max": round(float(el.max()) * 1e3, 3)}
            if self._phases is not None:
                out["phase_ms"] = {
                    p: {"mean": round(float(self._phases[idx, j].mean()) * 1e3, 3),
                        "max": round(float(self._phases[idx, j].max()) * 1e3, 3)}
                    for j, p in enumerate(self._phase_names)
                }
            if self._scored is not None:
                out["scored_by_group_window"] = [
                    int(x) for x in self._scored[idx].sum(axis=0)]
        try:
            from rtap_tpu.obs.expo import summarize_snapshot

            out["registry"] = summarize_snapshot(self.registry.snapshot())
        except Exception:  # noqa: BLE001 — a summary must not kill a dump
            out["registry"] = None
        if self.health_provider is not None:
            try:
                out["health"] = self.health_provider()
            except Exception:  # noqa: BLE001 — must not kill a dump
                out["health"] = None
        if self.latency_provider is not None:
            try:
                out["latency"] = self.latency_provider()
            except Exception:  # noqa: BLE001 — must not kill a dump
                out["latency"] = None
        if self.predict_provider is not None:
            try:
                out["predict"] = self.predict_provider()
            except Exception:  # noqa: BLE001 — must not kill a dump
                out["predict"] = None
        return out

    def dump(self, reason: str, tick: int | None = None) -> str | None:
        """Write one atomic postmortem bundle; returns its path, or None
        when throttled / no out_dir. Never raises: a failing disk must
        not take down the serve loop it is documenting. Thread-safe
        (loop thread + the obs server's /postmortem handler)."""
        with self._dump_lock:
            return self._dump_locked(reason, tick)

    def _dump_locked(self, reason: str, tick: int | None) -> str | None:
        tick = int(self._last_tick if tick is None else tick)
        if not self._allowed(reason, tick):
            self.dumps_skipped += 1
            self._obs_skipped.inc()
            return None
        t0 = time.perf_counter()
        safe = "".join(c if c.isalnum() or c in "-_" else "-"
                       for c in reason)[:48]
        name = (f"postmortem-{self._run_tag}-{len(self.bundles):03d}"
                f"-t{max(tick, 0):08d}-{safe}")
        final = os.path.join(self.out_dir, name)
        tmp = os.path.join(self.out_dir, f".tmp-{name}-{os.getpid()}")
        try:
            os.makedirs(tmp, exist_ok=True)
            # window the trace to the flight ring's tick span: the span
            # ring may hold more history than the bundle claims to cover
            idx = self._window()
            span_ticks = None
            if idx.size:
                span_ticks = int(self._last_tick - int(self._tick[idx[0]]) + 1)
            if self.trace is not None:
                with open(os.path.join(tmp, "trace.json"), "w") as f:
                    json.dump(self.trace.chrome_trace(last_ticks=span_ticks), f)
            with open(os.path.join(tmp, "events.jsonl"), "w") as f:
                # materialize first: list(deque) is one C-level copy
                # (GIL-atomic), while iterating the live deque races
                # the loop thread's record_event appends — a concurrent
                # mutation raises RuntimeError mid-dump
                for line in list(self._events):
                    f.write(line + "\n")
            with open(os.path.join(tmp, "summary.json"), "w") as f:
                json.dump(self.summary(reason, tick), f, indent=2)
            os.rename(tmp, final)
        except OSError:
            self.dumps_skipped += 1
            self._obs_skipped.inc()
            try:  # best-effort cleanup of the torn temp dir
                import shutil

                shutil.rmtree(tmp, ignore_errors=True)
            except Exception:  # noqa: BLE001
                pass
            return None
        self.bundles.append(final)
        self._last_dump_tick[reason] = tick
        c = self._obs_bundles.get(reason)
        if c is None:
            c = self._obs_bundles[reason] = self.registry.counter(
                "rtap_obs_postmortem_bundles_total",
                "postmortem bundles dumped, by trigger reason",
                reason=safe)
        c.inc()
        self._obs_last_tick.set(tick)
        self._obs_dump_seconds.observe(time.perf_counter() - t0)
        return final

    def stats(self) -> dict:
        """End-of-run accounting for the loop's stats dict."""
        return {
            "bundles": len(self.bundles),
            "bundle_paths": list(self.bundles),
            "dumps_skipped": self.dumps_skipped,
            "events_seen": self._events_total,
            "ticks_recorded": self._n,
        }


def validate_bundle(path: str) -> dict:
    """Machine-check one bundle: every file present and parseable, the
    trace is Chrome trace-event JSON with at least one complete span.
    Returns ``{"ok": bool, "problems": [...], "spans": n, "instants": n,
    "events": n, "reason": ..., "tick": ...}`` — the chaos soak and the
    tier-1 postmortem tests assert on it."""
    out: dict = {"ok": False, "problems": [], "spans": 0, "instants": 0,
                 "events": 0, "reason": None, "tick": None}
    if not os.path.isdir(path):
        out["problems"].append(f"not a directory: {path}")
        return out
    summary = None
    for fn in _BUNDLE_FILES:
        if not os.path.isfile(os.path.join(path, fn)):
            out["problems"].append(f"missing {fn}")
    try:
        with open(os.path.join(path, "summary.json")) as f:
            summary = json.load(f)
        out["reason"] = summary.get("reason")
        out["tick"] = summary.get("tick")
    except (OSError, ValueError) as e:
        out["problems"].append(f"summary.json unreadable: {e}")
    try:
        with open(os.path.join(path, "events.jsonl")) as f:
            for line in f:
                if line.strip():
                    json.loads(line)
                    out["events"] += 1
    except (OSError, ValueError) as e:
        out["problems"].append(f"events.jsonl unreadable: {e}")
    trace_expected = summary is None or summary.get("trace") is not None
    trace_path = os.path.join(path, "trace.json")
    if os.path.isfile(trace_path):
        try:
            with open(trace_path) as f:
                tj = json.load(f)
            evs = tj.get("traceEvents")
            if not isinstance(evs, list):
                out["problems"].append("trace.json has no traceEvents list")
            else:
                out["spans"] = sum(1 for e in evs if e.get("ph") == "X")
                out["instants"] = sum(1 for e in evs if e.get("ph") == "i")
                if out["spans"] == 0:
                    out["problems"].append("trace.json contains no spans")
        except (OSError, ValueError) as e:
            out["problems"].append(f"trace.json unreadable: {e}")
    elif trace_expected:
        out["problems"].append("missing trace.json")
    out["ok"] = not out["problems"]
    return out
