"""Tick watchdog: deadline/starvation/stall events for the live serve loop.

The 1 s-cadence north star is a REAL-TIME contract, and the soak forensics
showed its failures are structured, not noisy: warm-up compiles cost whole
ticks (9/3600 missed in the 1-hour soak), a dead feeder shows up as an
all-NaN source vector, and an inline checkpoint save eats a tick by design.
The watchdog consumes the loop's per-tick results and turns those shapes
into (a) registry counters and (b) structured JSONL events on the alert
stream — so a scraper sees ``rtap_obs_missed_ticks_total`` move and the
alert file says WHICH tick and WHY, without log-grepping.

Events (one JSON object per line, ``"event"`` key discriminates them from
alert records):

- ``missed_tick``      — a tick's host work exceeded the cadence budget
- ``source_starved``   — the source returned all-NaN ``starved_after``
  consecutive ticks (feeder dead / exporters down), and again every
  ``starved_after`` ticks while the outage lasts
- ``checkpoint_stall`` — an inline checkpoint save exceeded the cadence
  (expected occasionally; the event makes the cost attributable)
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from rtap_tpu.obs.metrics import TelemetryRegistry, get_registry

__all__ = ["TickWatchdog"]


class TickWatchdog:
    """Consumes per-tick facts from ``live_loop``; raises structured events.

    `event_sink` is any callable taking one JSON-able dict (the serve loop
    passes ``AlertWriter.emit_event`` so events ride the alert JSONL
    stream); None keeps counters only. All observe_* methods are called
    from the loop thread — no locking needed.
    """

    def __init__(self, cadence_s: float,
                 registry: TelemetryRegistry | None = None,
                 event_sink: Callable[[dict], None] | None = None,
                 starved_after: int = 3,
                 checkpoint_stall_s: float | None = None,
                 trace=None, flight=None):
        if starved_after < 1:
            raise ValueError(f"starved_after must be >= 1; got {starved_after}")
        reg = registry or get_registry()
        # optional timeline hooks (obs/trace.py, obs/flight.py): every
        # watchdog event also lands as an instant on the span timeline —
        # a missed_tick mark sits visually inside the tick that blew the
        # budget — and in the flight recorder's bounded event ring
        self._trace = trace
        self._flight = flight
        self.cadence_s = float(cadence_s)
        self.checkpoint_stall_s = float(
            checkpoint_stall_s if checkpoint_stall_s is not None else cadence_s)
        self.starved_after = int(starved_after)
        self._sink = event_sink
        self._starved_run = 0
        self._missed = reg.counter(
            "rtap_obs_missed_ticks_total",
            "ticks whose host work exceeded the cadence budget")
        self._events = {
            kind: reg.counter(
                "rtap_obs_watchdog_events_total",
                "structured watchdog events by kind", event=kind)
            for kind in ("missed_tick", "source_starved", "checkpoint_stall")
        }

    def set_cadence(self, cadence_s: float) -> None:
        """Adopt a new cadence mid-run (the degradation controller's
        tick_widen step changes the real-time contract, and misses must
        be judged against the contract actually in force). The stall
        budget follows proportionally when it was tracking the cadence;
        an explicit checkpoint_stall_s stays put."""
        tracking = self.checkpoint_stall_s == self.cadence_s
        self.cadence_s = float(cadence_s)
        if tracking:
            self.checkpoint_stall_s = self.cadence_s

    def _emit(self, kind: str, tick: int, **fields) -> None:
        self._events[kind].inc()
        if self._trace is not None:
            self._trace.add_instant(kind, int(tick), fields)
        if self._flight is not None:
            self._flight.record_event({"event": kind, "tick": int(tick),
                                       **fields})
        if self._sink is not None:
            self._sink({"event": kind, "tick": int(tick), **fields})

    def observe_tick(self, tick: int, elapsed_s: float) -> bool:
        """One tick's wall seconds vs the cadence budget; True = missed."""
        if elapsed_s <= self.cadence_s:
            return False
        self._missed.inc()
        self._emit("missed_tick", tick,
                   elapsed_s=round(float(elapsed_s), 6),
                   cadence_s=self.cadence_s)
        return True

    def observe_source(self, tick: int, values: np.ndarray) -> None:
        """One tick's polled value vector. An all-NaN vector is a tick with
        NO data from ANY stream — scored as missing samples by design, but
        `starved_after` in a row means the pipe itself is dead."""
        values = np.asarray(values)
        if values.size and bool(np.isnan(values).all()):
            self._starved_run += 1
            if self._starved_run % self.starved_after == 0:
                self._emit("source_starved", tick,
                           consecutive_ticks=self._starved_run)
        else:
            self._starved_run = 0

    def observe_checkpoint(self, tick: int, seconds: float) -> None:
        """One inline checkpoint save's wall seconds (drain + write)."""
        if seconds > self.checkpoint_stall_s:
            self._emit("checkpoint_stall", tick,
                       seconds=round(float(seconds), 6),
                       budget_s=self.checkpoint_stall_s)
