"""Analyzer framework: findings, suppressions, baseline, the runner.

The contracts this package enforces are the ones three separate review
passes kept re-discovering by hand (ISSUE 12): lock discipline across the
daemon-threaded serve modules, hot-path purity (device code must stay
deterministic and fetch-free; presence checks are not-NaN, never
isfinite), exception discipline in the serve stack, flag↔docs drift, and
the print gate. Each invariant is a *pass* (one module under
``rtap_tpu/analysis/``) producing :class:`Finding`s; this module owns
everything shared — file discovery/parsing, the per-finding suppression
comments, the committed baseline for grandfathered findings, and the
report the CLI renders.

Suppression syntax (docs/ANALYSIS.md):

    some_code()  # rtap: allow[rule-id] — one-line justification

A suppression covers findings of that rule on its own line and on the
line directly below (so a comment-only line can annotate the statement
it precedes). Several rules separate with commas:
``# rtap: allow[race,except-silent] — why``.

Baseline (``analysis_baseline.json`` at the repo root): grandfathered
findings keyed by ``(rule, path, symbol)`` — symbols are stable
(``Class.attr``, ``func:except OSError#2``), never line numbers, so
unrelated edits don't churn the file. Every entry MUST carry a
non-empty ``why``; a why-less entry is itself a finding. Entries that
no longer match anything are reported as stale (non-fatal — delete
them when you see them).
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass, field

__all__ = [
    "AnalysisContext",
    "Baseline",
    "Finding",
    "Report",
    "SourceFile",
    "discover_files",
    "render_human",
    "run_analysis",
]

#: the suppression comment grammar (see module docstring)
_SUPPRESS_RE = re.compile(r"#\s*rtap:\s*allow\[([A-Za-z0-9_,\s-]+)\]")

#: default baseline filename at the analysis root
BASELINE_NAME = "analysis_baseline.json"

#: the --json artifact's schema version. Bump on any shape change to
#: the artifact dict — soaks/hw_session archive these lines across
#: months and the reader must be able to dispatch on shape. v3
#: (ISSUE 14): cache gains the "warm" mode (pass-partitioned partial
#: reuse) and per_pass covers the device-kernel pass family. v4
#: (ISSUE 15): the mesh-readiness pass family lands (partition-contract,
#: device-scope, collective-discipline, shard-resource, scaling-math)
#: and SCALING.md joins the analyzer inputs.
SCHEMA_VERSION = 4

#: default findings-cache filename at the analysis root (gitignored)
CACHE_NAME = ".rtap_lint_cache.json"

#: bump to orphan every existing cache when the cache format changes
#: (2: ISSUE 14 — per-file pass partition section added; 3: ISSUE 15 —
#: SCALING.md hash joins the key)
_CACHE_FORMAT = 3

#: gate-critical rules that neither inline suppressions nor the baseline
#: may silence — the print gate is plumbing other gates stand on, and a
#: suppressible guard is no guard (the canary tests pin this)
NON_SUPPRESSIBLE = frozenset({"print-strict", "strict-coverage",
                              "parse-error"})


@dataclass
class Finding:
    """One invariant violation at one site."""

    rule: str          # pass rule id, e.g. "race", "except-silent"
    path: str          # repo-relative posix path
    line: int          # 1-based line of the offending node
    symbol: str        # stable key within the file (line-insensitive)
    message: str       # human explanation with the fix direction

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message}


class SourceFile:
    """One parsed python file + its suppression comments.

    ``path`` is repo-relative (posix separators) — it decides pass scope
    (tests build synthetic paths to land fixture snippets in scope).
    Files that fail to parse record ``parse_error`` instead of a tree;
    the runner turns that into a finding (compileall would catch it too,
    but the analyzer must never crash on a torn working tree).
    """

    def __init__(self, path: str, text: str):
        self.path = path.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: str | None = None
        try:
            self.tree: ast.AST | None = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"{type(e).__name__}: {e}"
        self._suppressions: dict[int, set[str]] | None = None

    @property
    def suppressions(self) -> dict[int, set[str]]:
        """line -> rule ids suppressed there. Comments live outside the
        AST, so tokenize finds them (including trailing ones) — LAZILY:
        only files that actually have findings pay the tokenize pass
        (~half the parse cost fleet-wide, and most files have none)."""
        if self._suppressions is None:
            self._suppressions = {}
            if self.parse_error is None and "rtap:" in self.text:
                try:
                    for tok in tokenize.generate_tokens(
                            io.StringIO(self.text).readline):
                        if tok.type != tokenize.COMMENT:
                            continue
                        m = _SUPPRESS_RE.search(tok.string)
                        if m is None:
                            continue
                        rules = {r.strip() for r in m.group(1).split(",")
                                 if r.strip()}
                        self._suppressions.setdefault(
                            tok.start[0], set()).update(rules)
                except tokenize.TokenError:
                    pass  # ast accepted it; worst case this file's
                    # suppression comments are not honored (fails loud)
        return self._suppressions

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding is suppressed by a comment on its line or on the
        line directly above (the comment-on-its-own-line form)."""
        for ln in (line, line - 1):
            if rule in self.suppressions.get(ln, ()):
                return True
        return False


@dataclass
class AnalysisContext:
    """Everything a pass may consult."""

    root: str
    files: list[SourceFile]
    #: README + docs/**.md concatenated (flag↔docs pass); lazily loaded,
    #: overridable by tests
    docs_text: str | None = None
    #: tests/parity/**.py concatenated (twin-parity pass — deleting a
    #: parity test must re-fail the gate, so the parity tree is an
    #: analyzer INPUT and rides the cache key like the docs text)
    parity_text: str | None = None
    #: SCALING.md at the repo root (scaling-math pass, ISSUE 15: the
    #: quoted bytes/stream numbers are cross-checked against a static
    #: derivation from the config dataclasses — editing the doc must
    #: re-run the pass, so it is an analyzer INPUT like the docs text)
    scaling_text: str | None = None

    def files_under(self, *prefixes: str) -> list[SourceFile]:
        return [f for f in self.files
                if any(f.path.startswith(p) for p in prefixes)]

    def file(self, path: str) -> SourceFile | None:
        for f in self.files:
            if f.path == path:
                return f
        return None

    def docs(self) -> str:
        # ONE loader shared with the cache key (_docs_text): the flags
        # pass must analyze exactly the text the cache hashed, or a
        # docs-only edit could be served a stale green hit
        if self.docs_text is None:
            self.docs_text = _docs_text(self.root)
        return self.docs_text

    def parity(self) -> str:
        # same single-loader discipline as docs(): the twin-parity pass
        # must see exactly the text the cache key hashed
        if self.parity_text is None:
            self.parity_text = _parity_text(self.root)
        return self.parity_text

    def scaling(self) -> str:
        # same single-loader discipline again (scaling-math pass)
        if self.scaling_text is None:
            self.scaling_text = _scaling_text(self.root)
        return self.scaling_text


class Baseline:
    """The committed grandfathered-findings file (see module docstring)."""

    def __init__(self, entries: list[dict], path: str | None = None):
        self.path = path
        self.entries = entries
        self.format_errors: list[str] = []
        self._index: dict[tuple[str, str, str], dict] = {}
        self._used: set[tuple[str, str, str]] = set()
        for i, e in enumerate(entries):
            rule, p, sym = (e.get("rule"), e.get("path"), e.get("symbol"))
            if not (rule and p and sym):
                self.format_errors.append(
                    f"entry #{i} missing rule/path/symbol: {e!r}")
                continue
            if not str(e.get("why", "")).strip():
                self.format_errors.append(
                    f"entry #{i} ({rule}:{p}:{sym}) has no 'why' — every "
                    "baseline entry must carry a justification")
                continue
            self._index[(rule, p, sym)] = e

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except FileNotFoundError:
            return cls([], path)
        except (OSError, ValueError) as e:
            b = cls([], path)
            b.format_errors.append(f"unreadable baseline {path}: {e}")
            return b
        entries = data.get("entries", []) if isinstance(data, dict) else []
        if not isinstance(entries, list):
            b = cls([], path)
            b.format_errors.append(
                f"baseline {path}: 'entries' must be a list")
            return b
        return cls(entries, path)

    def matches(self, finding: Finding) -> bool:
        k = finding.key()
        if k in self._index:
            self._used.add(k)
            return True
        return False

    def stale_entries(self) -> list[dict]:
        return [e for k, e in sorted(self._index.items())
                if k not in self._used]


def discover_texts(root: str) -> list[tuple[str, str]]:
    """(repo-relative path, text) for the analysis surface: every .py
    under rtap_tpu/ and scripts/, plus bench.py — the same set the old
    check_static.sh walked, so the print gate's coverage is unchanged.
    Split from parsing so the findings cache can judge freshness from
    content hashes WITHOUT paying ~100 ast.parse calls on a hit."""
    out: list[tuple[str, str]] = []
    for top in ("rtap_tpu", "scripts"):
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            # sorted: os.walk's subdir order is filesystem-arbitrary,
            # and the whole-program model's first-definition-wins (and
            # finding/report order generally) must not vary across
            # hosts — the analyzer holds itself to its own
            # replay-determinism rule
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                with open(full, encoding="utf-8") as fh:
                    out.append((rel, fh.read()))
    bench = os.path.join(root, "bench.py")
    if os.path.isfile(bench):
        with open(bench, encoding="utf-8") as fh:
            out.append(("bench.py", fh.read()))
    return out


def discover_files(root: str) -> list[SourceFile]:
    return [SourceFile(p, t) for p, t in discover_texts(root)]


@dataclass
class Report:
    """The runner's result: what the CLI renders and the gate asserts."""

    findings: list[Finding]          # unsuppressed, the gate's subject
    suppressed: list[Finding]        # silenced by inline comments
    baselined: list[Finding]         # silenced by the baseline file
    stale_baseline: list[dict]       # baseline entries matching nothing
    baseline_errors: list[str]       # malformed baseline entries (fatal)
    per_pass: dict = field(default_factory=dict)  # pass -> raw count
    elapsed_s: float = 0.0
    files_scanned: int = 0
    #: "cold" (full run, cache written), "hit" (replayed from the
    #: content-hash cache), "warm" (per-file passes reused for the
    #: unchanged files, whole-program passes re-run — ISSUE 14), "off"
    #: (cache not engaged: fixtures, --rules subsets, --no-cache)
    cache_mode: str = "off"

    @property
    def ok(self) -> bool:
        return not self.findings and not self.baseline_errors

    def to_dict(self) -> dict:
        """The --json artifact line (soaks/hw_session archive this)."""
        return {
            "analysis": {
                "schema_version": SCHEMA_VERSION,
                "ok": self.ok,
                "files_scanned": self.files_scanned,
                "elapsed_s": round(self.elapsed_s, 3),
                "cache": self.cache_mode,
                "findings": [f.to_dict() for f in self.findings],
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "stale_baseline": self.stale_baseline,
                "baseline_errors": self.baseline_errors,
                "per_pass": dict(sorted(self.per_pass.items())),
            }
        }


# --------------------------------------------------------------- cache --
# The findings cache, pass-PARTITIONED since ISSUE 14. Whole-program
# passes (lock-order, cross-share, twin-parity, donation,
# wire-contract) make per-file findings reuse unsound for THEM — one
# edited file can add or remove an edge whose finding anchors in
# another file — so they stay all-or-nothing. Per-file passes
# (PARTITION = "file": races, purity, excepts, determinism, lifecycle,
# trace-safety, static-hash, dtype-domain) produce findings that
# depend only on one file's bytes, so the cache additionally stores
# their raw findings PER FILE and replays them for every unchanged
# file while only the edited files re-run — the "warm" mode that keeps
# incremental runs ~2 s with the full pass family live. The exact-hit
# fast path is unchanged: when EVERY input is byte-identical (file
# hashes, docs text, parity-test text, baseline, analyzer sources) the
# classified report replays with no parsing at all. Classification
# (suppressions/baseline) is always re-derived from raw findings — a
# baseline edit must never be served a stale verdict. All three modes
# are finding-identical by test (tests/unit/test_static_checks.py).

def _sha(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()[:20]


def _analyzer_fingerprint() -> str:
    """Hash of the analysis package's own sources: editing a pass must
    orphan the cache, or a tightened rule would silently not re-run."""
    here = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    for fn in sorted(os.listdir(here)):
        if fn.endswith(".py"):
            with open(os.path.join(here, fn), "rb") as fh:
                h.update(fn.encode() + b"\0")
                h.update(fh.read() + b"\0")
    return h.hexdigest()[:20]


def _docs_text(root: str) -> str:
    chunks = []
    p = os.path.join(root, "README.md")
    if os.path.isfile(p):
        with open(p, encoding="utf-8") as fh:
            chunks.append(fh.read())
    docs_dir = os.path.join(root, "docs")
    if os.path.isdir(docs_dir):
        for fn in sorted(os.listdir(docs_dir)):
            if fn.endswith(".md"):
                with open(os.path.join(docs_dir, fn),
                          encoding="utf-8") as fh:
                    chunks.append(fh.read())
    return "\n".join(chunks)


def _parity_text(root: str) -> str:
    """tests/parity/**.py concatenated — the twin-parity pass's
    test-coverage evidence (and a cache-key input for the same reason
    the docs text is)."""
    chunks = []
    pdir = os.path.join(root, "tests", "parity")
    if os.path.isdir(pdir):
        for fn in sorted(os.listdir(pdir)):
            if fn.endswith(".py"):
                with open(os.path.join(pdir, fn),
                          encoding="utf-8") as fh:
                    chunks.append(fh.read())
    return "\n".join(chunks)


def _scaling_text(root: str) -> str:
    """SCALING.md at the repo root — the scaling-math pass's
    cross-check subject (and a cache-key input for the same reason the
    docs text is; it lives at the root, outside _docs_text's walk)."""
    p = os.path.join(root, "SCALING.md")
    if os.path.isfile(p):
        with open(p, encoding="utf-8") as fh:
            return fh.read()
    return ""


def _cache_key(texts: list[tuple[str, str]], docs: str, parity: str,
               scaling: str, baseline_path: str) -> dict:
    try:
        with open(baseline_path, encoding="utf-8") as fh:
            baseline_hash = _sha(fh.read())
    except OSError:
        baseline_hash = "absent"
    return {
        "format": _CACHE_FORMAT,
        "analyzer": _analyzer_fingerprint(),
        "files": {p: _sha(t) for p, t in texts},
        "docs": _sha(docs),
        "parity": _sha(parity),
        "scaling": _sha(scaling),
        "baseline": baseline_hash,
    }


def _report_to_cache(report: Report) -> dict:
    return {
        "findings": [f.to_dict() for f in report.findings],
        "suppressed": [f.to_dict() for f in report.suppressed],
        "baselined": [f.to_dict() for f in report.baselined],
        "stale_baseline": report.stale_baseline,
        "baseline_errors": report.baseline_errors,
        "per_pass": report.per_pass,
        "files_scanned": report.files_scanned,
    }


def _report_from_cache(data: dict, elapsed_s: float) -> Report:
    def fs(key):
        return [Finding(**d) for d in data[key]]

    return Report(
        findings=fs("findings"), suppressed=fs("suppressed"),
        baselined=fs("baselined"),
        stale_baseline=data["stale_baseline"],
        baseline_errors=data["baseline_errors"],
        per_pass=data["per_pass"], elapsed_s=elapsed_s,
        files_scanned=data["files_scanned"], cache_mode="hit")


def run_analysis_cached(root: str, baseline_path: str | None = None,
                        cache_path: str | None = None) -> Report:
    """The CLI's full-run entry point. Three speeds:

    * **hit** — every input byte-identical: replay the classified
      report, no parsing at all;
    * **warm** — same analyzer, some files changed: per-file passes
      re-run only on the changed files (cached raw findings replayed
      for the rest), whole-program passes re-run in full;
    * **cold** — no usable cache (format/analyzer change, first run).

    ``--rules`` subsets and fixture contexts never come through here —
    the cache only ever holds full-tree reports."""
    from rtap_tpu.analysis import PASSES

    t0 = time.perf_counter()
    baseline_path = baseline_path or os.path.join(root, BASELINE_NAME)
    cache_path = cache_path or os.path.join(root, CACHE_NAME)
    texts = discover_texts(root)
    docs = _docs_text(root)
    parity = _parity_text(root)
    scaling = _scaling_text(root)
    key = _cache_key(texts, docs, parity, scaling, baseline_path)
    try:
        with open(cache_path, encoding="utf-8") as fh:
            cached = json.load(fh)
    except (OSError, ValueError):
        cached = None
    if isinstance(cached, dict) and cached.get("key") == key:
        return _report_from_cache(
            cached["report"], time.perf_counter() - t0)

    # ---- partial (warm) reuse: unchanged files keep their per-file-
    # pass raw findings; only edited files pay the per-file passes
    reuse: dict[str, dict] = {}
    if isinstance(cached, dict) and isinstance(cached.get("key"), dict) \
            and cached["key"].get("format") == _CACHE_FORMAT \
            and cached["key"].get("analyzer") == key["analyzer"] \
            and isinstance(cached.get("perfile"), dict):
        old_hashes = cached["key"].get("files", {})
        for p, h in key["files"].items():
            if old_hashes.get(p) == h and p in cached["perfile"]:
                reuse[p] = cached["perfile"][p]

    files = [SourceFile(p, t) for p, t in texts]
    ctx = AnalysisContext(root=root, files=files, docs_text=docs,
                          parity_text=parity, scaling_text=scaling)
    baseline = Baseline.load(baseline_path)
    file_passes = [m for m in PASSES
                   if getattr(m, "PARTITION", "program") == "file"]
    program_passes = [m for m in PASSES if m not in file_passes]

    raw: list[Finding] = []
    per_pass: dict[str, int] = {m.PASS_NAME: 0 for m in PASSES}
    pass_of = {rid: m.PASS_NAME for m in file_passes for rid in m.RULES}
    perfile: dict[str, dict] = {}
    changed = [f for f in files if f.path not in reuse]
    sub = AnalysisContext(root=root, files=changed, docs_text=docs,
                          parity_text=parity, scaling_text=scaling)
    fresh_raw, fresh_counts = _run_passes(sub, file_passes)
    for p, n in fresh_counts.items():
        per_pass[p] += n
    for f in changed:
        perfile[f.path] = {}
    for fi in fresh_raw:
        perfile.setdefault(fi.path, {}).setdefault(
            pass_of.get(fi.rule, fi.rule), []).append(fi.to_dict())
        raw.append(fi)
    for path, bucket in reuse.items():
        perfile[path] = bucket
        for pname, dicts in bucket.items():
            per_pass[pname] = per_pass.get(pname, 0) + len(dicts)
            raw.extend(Finding(**d) for d in dicts)
    prog_raw, prog_counts = _run_passes(ctx, program_passes)
    per_pass.update(prog_counts)
    raw.extend(prog_raw)

    report = _classify(raw, ctx, baseline, rules=None,
                       per_pass=per_pass)
    report.elapsed_s = time.perf_counter() - t0
    report.cache_mode = "warm" if reuse else "cold"
    tmp = f"{cache_path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"key": key, "report": _report_to_cache(report),
                       "perfile": perfile}, fh)
        os.replace(tmp, cache_path)
    except OSError:
        # an unwritable cache (read-only checkout) costs the NEXT run
        # its speedup, never this run its correctness
        try:
            os.remove(tmp)
        except OSError:
            pass
    return report


def _run_passes(ctx: AnalysisContext, passes) -> tuple[list[Finding],
                                                       dict[str, int]]:
    raw: list[Finding] = []
    per_pass: dict[str, int] = {}
    for mod in passes:
        found = mod.run(ctx)
        per_pass[mod.PASS_NAME] = len(found)
        raw.extend(found)
    return raw, per_pass


def _classify(raw: list[Finding], ctx: AnalysisContext,
              baseline: Baseline, rules: set[str] | None,
              per_pass: dict[str, int]) -> Report:
    """Suppression/baseline classification over raw findings (always
    re-derived — cached raw findings must never carry a stale
    verdict). Parse errors are appended here: a file that does not
    parse is a finding too (the analyzer must degrade loudly, not
    crash or silently skip)."""
    raw = list(raw)
    for f in ctx.files:
        if f.parse_error is not None:
            raw.append(Finding(
                rule="parse-error", path=f.path, line=1,
                symbol="module", message=f.parse_error))
    findings: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    for fi in raw:
        if rules is not None and fi.rule not in rules:
            continue
        sf = ctx.file(fi.path)
        if fi.rule in NON_SUPPRESSIBLE:
            findings.append(fi)
        elif sf is not None and sf.suppressed(fi.rule, fi.line):
            suppressed.append(fi)
        elif baseline.matches(fi):
            baselined.append(fi)
        else:
            findings.append(fi)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    suppressed.sort(key=lambda f: (f.path, f.line, f.rule))
    baselined.sort(key=lambda f: (f.path, f.line, f.rule))
    # staleness is only judgeable on a FULL run: a --rules subset never
    # consults the baseline for the unselected rules, and reporting
    # their (valid) entries as stale would advise deleting them
    return Report(
        findings=findings, suppressed=suppressed, baselined=baselined,
        stale_baseline=baseline.stale_entries() if rules is None else [],
        baseline_errors=list(baseline.format_errors),
        per_pass=per_pass, files_scanned=len(ctx.files))


def run_analysis(root: str, files: list[SourceFile] | None = None,
                 baseline: Baseline | None = None,
                 rules: set[str] | None = None,
                 ctx: AnalysisContext | None = None) -> Report:
    """Run every pass over the tree; classify findings against inline
    suppressions and the baseline. `files`/`ctx` injection is the unit
    tests' fixture seam; `rules` filters to a subset of rule ids."""
    from rtap_tpu.analysis import PASSES

    t0 = time.perf_counter()
    if ctx is None:
        if files is None:
            files = discover_files(root)
        ctx = AnalysisContext(root=root, files=files)
    if baseline is None:
        baseline = Baseline.load(os.path.join(root, BASELINE_NAME))
    raw, per_pass = _run_passes(ctx, PASSES)
    report = _classify(raw, ctx, baseline, rules, per_pass)
    report.elapsed_s = time.perf_counter() - t0
    return report


def render_human(report: Report) -> str:
    """The stderr report: one line per finding, then the tallies."""
    lines: list[str] = []
    for f in report.findings:
        lines.append(f"{f.path}:{f.line}: [{f.rule}] {f.symbol}: "
                     f"{f.message}")
    for e in report.baseline_errors:
        lines.append(f"analysis_baseline.json: [baseline-format] {e}")
    for e in report.stale_baseline:
        lines.append(
            f"analysis_baseline.json: stale entry "
            f"{e.get('rule')}:{e.get('path')}:{e.get('symbol')} matches "
            "nothing — delete it (non-fatal)")
    lines.append(
        f"rtap-lint: {len(report.findings)} finding(s), "
        f"{len(report.suppressed)} suppressed, "
        f"{len(report.baselined)} baselined, "
        f"{report.files_scanned} files in {report.elapsed_s:.2f}s "
        f"({'OK' if report.ok else 'FAIL'})")
    return "\n".join(lines)
