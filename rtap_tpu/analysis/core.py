"""Analyzer framework: findings, suppressions, baseline, the runner.

The contracts this package enforces are the ones three separate review
passes kept re-discovering by hand (ISSUE 12): lock discipline across the
daemon-threaded serve modules, hot-path purity (device code must stay
deterministic and fetch-free; presence checks are not-NaN, never
isfinite), exception discipline in the serve stack, flag↔docs drift, and
the print gate. Each invariant is a *pass* (one module under
``rtap_tpu/analysis/``) producing :class:`Finding`s; this module owns
everything shared — file discovery/parsing, the per-finding suppression
comments, the committed baseline for grandfathered findings, and the
report the CLI renders.

Suppression syntax (docs/ANALYSIS.md):

    some_code()  # rtap: allow[rule-id] — one-line justification

A suppression covers findings of that rule on its own line and on the
line directly below (so a comment-only line can annotate the statement
it precedes). Several rules separate with commas:
``# rtap: allow[race,except-silent] — why``.

Baseline (``analysis_baseline.json`` at the repo root): grandfathered
findings keyed by ``(rule, path, symbol)`` — symbols are stable
(``Class.attr``, ``func:except OSError#2``), never line numbers, so
unrelated edits don't churn the file. Every entry MUST carry a
non-empty ``why``; a why-less entry is itself a finding. Entries that
no longer match anything are reported as stale (non-fatal — delete
them when you see them).
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import time
import tokenize
from dataclasses import dataclass, field

__all__ = [
    "AnalysisContext",
    "Baseline",
    "Finding",
    "Report",
    "SourceFile",
    "discover_files",
    "render_human",
    "run_analysis",
]

#: the suppression comment grammar (see module docstring)
_SUPPRESS_RE = re.compile(r"#\s*rtap:\s*allow\[([A-Za-z0-9_,\s-]+)\]")

#: default baseline filename at the analysis root
BASELINE_NAME = "analysis_baseline.json"

#: gate-critical rules that neither inline suppressions nor the baseline
#: may silence — the print gate is plumbing other gates stand on, and a
#: suppressible guard is no guard (the canary tests pin this)
NON_SUPPRESSIBLE = frozenset({"print-strict", "strict-coverage",
                              "parse-error"})


@dataclass
class Finding:
    """One invariant violation at one site."""

    rule: str          # pass rule id, e.g. "race", "except-silent"
    path: str          # repo-relative posix path
    line: int          # 1-based line of the offending node
    symbol: str        # stable key within the file (line-insensitive)
    message: str       # human explanation with the fix direction

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message}


class SourceFile:
    """One parsed python file + its suppression comments.

    ``path`` is repo-relative (posix separators) — it decides pass scope
    (tests build synthetic paths to land fixture snippets in scope).
    Files that fail to parse record ``parse_error`` instead of a tree;
    the runner turns that into a finding (compileall would catch it too,
    but the analyzer must never crash on a torn working tree).
    """

    def __init__(self, path: str, text: str):
        self.path = path.replace(os.sep, "/")
        self.text = text
        self.lines = text.splitlines()
        self.parse_error: str | None = None
        try:
            self.tree: ast.AST | None = ast.parse(text, filename=path)
        except SyntaxError as e:
            self.tree = None
            self.parse_error = f"{type(e).__name__}: {e}"
        # line -> set of rule ids suppressed there (comments live outside
        # the AST: tokenize finds them, including trailing ones)
        self.suppressions: dict[int, set[str]] = {}
        if self.parse_error is None:
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(text).readline):
                    if tok.type != tokenize.COMMENT:
                        continue
                    m = _SUPPRESS_RE.search(tok.string)
                    if m is None:
                        continue
                    rules = {r.strip() for r in m.group(1).split(",")
                             if r.strip()}
                    self.suppressions.setdefault(
                        tok.start[0], set()).update(rules)
            except tokenize.TokenError:
                pass  # ast accepted it; worst case this file's
                # suppression comments are not honored (fails loud)

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding is suppressed by a comment on its line or on the
        line directly above (the comment-on-its-own-line form)."""
        for ln in (line, line - 1):
            if rule in self.suppressions.get(ln, ()):
                return True
        return False


@dataclass
class AnalysisContext:
    """Everything a pass may consult."""

    root: str
    files: list[SourceFile]
    #: README + docs/**.md concatenated (flag↔docs pass); lazily loaded,
    #: overridable by tests
    docs_text: str | None = None

    def files_under(self, *prefixes: str) -> list[SourceFile]:
        return [f for f in self.files
                if any(f.path.startswith(p) for p in prefixes)]

    def file(self, path: str) -> SourceFile | None:
        for f in self.files:
            if f.path == path:
                return f
        return None

    def docs(self) -> str:
        if self.docs_text is None:
            chunks = []
            for name in ("README.md",):
                p = os.path.join(self.root, name)
                if os.path.isfile(p):
                    with open(p, encoding="utf-8") as fh:
                        chunks.append(fh.read())
            docs_dir = os.path.join(self.root, "docs")
            if os.path.isdir(docs_dir):
                for fn in sorted(os.listdir(docs_dir)):
                    if fn.endswith(".md"):
                        with open(os.path.join(docs_dir, fn),
                                  encoding="utf-8") as fh:
                            chunks.append(fh.read())
            self.docs_text = "\n".join(chunks)
        return self.docs_text


class Baseline:
    """The committed grandfathered-findings file (see module docstring)."""

    def __init__(self, entries: list[dict], path: str | None = None):
        self.path = path
        self.entries = entries
        self.format_errors: list[str] = []
        self._index: dict[tuple[str, str, str], dict] = {}
        self._used: set[tuple[str, str, str]] = set()
        for i, e in enumerate(entries):
            rule, p, sym = (e.get("rule"), e.get("path"), e.get("symbol"))
            if not (rule and p and sym):
                self.format_errors.append(
                    f"entry #{i} missing rule/path/symbol: {e!r}")
                continue
            if not str(e.get("why", "")).strip():
                self.format_errors.append(
                    f"entry #{i} ({rule}:{p}:{sym}) has no 'why' — every "
                    "baseline entry must carry a justification")
                continue
            self._index[(rule, p, sym)] = e

    @classmethod
    def load(cls, path: str) -> "Baseline":
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except FileNotFoundError:
            return cls([], path)
        except (OSError, ValueError) as e:
            b = cls([], path)
            b.format_errors.append(f"unreadable baseline {path}: {e}")
            return b
        entries = data.get("entries", []) if isinstance(data, dict) else []
        if not isinstance(entries, list):
            b = cls([], path)
            b.format_errors.append(
                f"baseline {path}: 'entries' must be a list")
            return b
        return cls(entries, path)

    def matches(self, finding: Finding) -> bool:
        k = finding.key()
        if k in self._index:
            self._used.add(k)
            return True
        return False

    def stale_entries(self) -> list[dict]:
        return [e for k, e in sorted(self._index.items())
                if k not in self._used]


def discover_files(root: str) -> list[SourceFile]:
    """The analysis surface: every .py under rtap_tpu/ and scripts/,
    plus bench.py — the same set the old check_static.sh walked, so the
    print gate's coverage is unchanged by the port."""
    out: list[SourceFile] = []
    for top in ("rtap_tpu", "scripts"):
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                with open(full, encoding="utf-8") as fh:
                    out.append(SourceFile(rel, fh.read()))
    bench = os.path.join(root, "bench.py")
    if os.path.isfile(bench):
        with open(bench, encoding="utf-8") as fh:
            out.append(SourceFile("bench.py", fh.read()))
    return out


@dataclass
class Report:
    """The runner's result: what the CLI renders and the gate asserts."""

    findings: list[Finding]          # unsuppressed, the gate's subject
    suppressed: list[Finding]        # silenced by inline comments
    baselined: list[Finding]         # silenced by the baseline file
    stale_baseline: list[dict]       # baseline entries matching nothing
    baseline_errors: list[str]       # malformed baseline entries (fatal)
    per_pass: dict = field(default_factory=dict)  # pass -> raw count
    elapsed_s: float = 0.0
    files_scanned: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.baseline_errors

    def to_dict(self) -> dict:
        """The --json artifact line (soaks/hw_session archive this)."""
        return {
            "analysis": {
                "ok": self.ok,
                "files_scanned": self.files_scanned,
                "elapsed_s": round(self.elapsed_s, 3),
                "findings": [f.to_dict() for f in self.findings],
                "suppressed": len(self.suppressed),
                "baselined": len(self.baselined),
                "stale_baseline": self.stale_baseline,
                "baseline_errors": self.baseline_errors,
                "per_pass": dict(sorted(self.per_pass.items())),
            }
        }


def run_analysis(root: str, files: list[SourceFile] | None = None,
                 baseline: Baseline | None = None,
                 rules: set[str] | None = None,
                 ctx: AnalysisContext | None = None) -> Report:
    """Run every pass over the tree; classify findings against inline
    suppressions and the baseline. `files`/`ctx` injection is the unit
    tests' fixture seam; `rules` filters to a subset of rule ids."""
    from rtap_tpu.analysis import PASSES

    t0 = time.perf_counter()
    if ctx is None:
        if files is None:
            files = discover_files(root)
        ctx = AnalysisContext(root=root, files=files)
    if baseline is None:
        baseline = Baseline.load(os.path.join(root, BASELINE_NAME))

    raw: list[Finding] = []
    per_pass: dict[str, int] = {}
    for mod in PASSES:
        found = mod.run(ctx)
        per_pass[mod.PASS_NAME] = len(found)
        raw.extend(found)
    # a file that does not parse is a finding too (the analyzer must
    # degrade loudly, not crash or silently skip)
    for f in ctx.files:
        if f.parse_error is not None:
            raw.append(Finding(
                rule="parse-error", path=f.path, line=1,
                symbol="module", message=f.parse_error))

    findings: list[Finding] = []
    suppressed: list[Finding] = []
    baselined: list[Finding] = []
    for fi in raw:
        if rules is not None and fi.rule not in rules:
            continue
        sf = ctx.file(fi.path)
        if fi.rule in NON_SUPPRESSIBLE:
            findings.append(fi)
        elif sf is not None and sf.suppressed(fi.rule, fi.line):
            suppressed.append(fi)
        elif baseline.matches(fi):
            baselined.append(fi)
        else:
            findings.append(fi)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    # staleness is only judgeable on a FULL run: a --rules subset never
    # consults the baseline for the unselected rules, and reporting
    # their (valid) entries as stale would advise deleting them
    return Report(
        findings=findings, suppressed=suppressed, baselined=baselined,
        stale_baseline=baseline.stale_entries() if rules is None else [],
        baseline_errors=list(baseline.format_errors),
        per_pass=per_pass, elapsed_s=time.perf_counter() - t0,
        files_scanned=len(ctx.files))


def render_human(report: Report) -> str:
    """The stderr report: one line per finding, then the tallies."""
    lines: list[str] = []
    for f in report.findings:
        lines.append(f"{f.path}:{f.line}: [{f.rule}] {f.symbol}: "
                     f"{f.message}")
    for e in report.baseline_errors:
        lines.append(f"analysis_baseline.json: [baseline-format] {e}")
    for e in report.stale_baseline:
        lines.append(
            f"analysis_baseline.json: stale entry "
            f"{e.get('rule')}:{e.get('path')}:{e.get('symbol')} matches "
            "nothing — delete it (non-fatal)")
    lines.append(
        f"rtap-lint: {len(report.findings)} finding(s), "
        f"{len(report.suppressed)} suppressed, "
        f"{len(report.baselined)} baselined, "
        f"{report.files_scanned} files in {report.elapsed_s:.2f}s "
        f"({'OK' if report.ok else 'FAIL'})")
    return "\n".join(lines)
