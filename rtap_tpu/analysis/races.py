"""Thread-shared-state race detection + thread-naming discipline.

Rule ``race`` — for each class in the serve stack that runs code on its
own thread (a ``threading.Thread(target=self.m)`` / ``target=<nested
function>`` spawn, or a nested ``socketserver``-style request-handler
class whose methods run per-connection), compute the ``self.*``
attributes WRITTEN from the thread-entry call graph and from the
main-side (public) methods, and flag attributes mutated on both sides
whose write paths do not all share a common ``with self._lock``-style
guard. Request-handler threads are concurrent with THEMSELVES (one per
connection), so any unguarded handler-side write is a race even without
a main-side writer — exactly the ``outer._py_parse_errors += 1``
lost-update class this pass was built from.

Guard reasoning is interprocedural within the class: a private method
whose every in-class call site sits inside ``with self._lock`` inherits
the guard (the ``BinaryBatchSource._apply`` idiom — callers hold the
lock), computed as the intersection of guards over all call paths from
the side's entry points (a method reachable both with and without the
lock counts as unguarded).

What this pass deliberately does NOT flag (docs/ANALYSIS.md triage):
single-writer attributes read unguarded from the other side (GIL-atomic
scalar reads are the serve stack's documented telemetry tolerance), and
cross-OBJECT sharing (HealthTracker.fold vs the obs server's snapshot
thread — those contracts are audited by hand and documented on the
class). Writes in ``__init__`` are construction-time (before any thread
starts) and ignored.

Rule ``thread-name`` — every ``threading.Thread``/``Timer`` spawned in
the serve stack must carry ``name="rtap-<module>-<role>"`` so race
findings, the conftest thread-leak fixture, and stuck-session triage
attribute threads to owners.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from rtap_tpu.analysis.core import AnalysisContext, Finding

PASS_NAME = "races"
#: findings depend only on one file's bytes -> the warm
#: cache may replay them per file (core.py partition contract)
PARTITION = "file"
RULES = {
    "race": "self.* attribute mutated from both a spawned thread and "
            "main-side methods without a common lock guard on every "
            "write path",
    "thread-name": "threading.Thread spawned in the serve stack without "
                   'a name="rtap-<module>-<role>"',
}

#: the serve stack (same scope as the strict print gate)
SCOPE = ("rtap_tpu/service/", "rtap_tpu/obs/", "rtap_tpu/resilience/",
         "rtap_tpu/ingest/", "rtap_tpu/correlate/", "rtap_tpu/fleet/")

#: attribute-method calls that mutate their receiver in place
MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "update", "setdefault", "pop", "popitem", "popleft",
    "clear", "sort", "reverse",
})

#: a ``with self.<g>`` guards writes when <g> smells like a lock
GUARD_HINTS = ("lock", "cond", "mutex")


def _dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_thread_ctor(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return d in ("threading.Thread", "Thread",
                 "threading.Timer", "Timer")


@dataclass
class _Write:
    attr: str
    line: int
    guards: frozenset  # lexical guards at the write site


@dataclass
class _MethodInfo:
    name: str
    writes: list[_Write] = field(default_factory=list)
    #: (callee method name, lexical guards at the call site)
    calls: list[tuple[str, frozenset]] = field(default_factory=list)


class _BodyScanner(ast.NodeVisitor):
    """Scan one method/function body for self-attr writes, self-method
    calls, and the lexical ``with <self-ish>.<lock>`` guard stack.
    Nested function/class definitions are NOT descended into (they run
    later, on whoever calls them — thread-target nested functions are
    scanned separately as thread entries)."""

    def __init__(self, self_names: set[str], method_names: set[str]):
        self.self_names = self_names
        self.method_names = method_names
        self.info = _MethodInfo(name="")
        self._guards: list[str] = []

    # -- structure we do not descend into ------------------------------
    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):  # noqa: N802
        pass

    def visit_Lambda(self, node):  # noqa: N802
        pass

    # -- guards --------------------------------------------------------
    def _guard_of(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id in self.self_names \
                and any(h in expr.attr.lower() for h in GUARD_HINTS):
            return expr.attr
        return None

    def visit_With(self, node):  # noqa: N802
        names = [g for g in (self._guard_of(it.context_expr)
                             for it in node.items) if g is not None]
        self._guards.extend(names)
        for st in node.body:
            self.visit(st)
        if names:
            del self._guards[-len(names):]

    # -- writes --------------------------------------------------------
    def _self_attr_of_target(self, t: ast.AST) -> str | None:
        # self.x = / self.x[...] = / del self.x
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id in self.self_names:
            return t.attr
        if isinstance(t, ast.Subscript):
            return self._self_attr_of_target(t.value)
        return None

    def _record_write(self, attr: str | None, line: int) -> None:
        if attr is not None:
            self.info.writes.append(
                _Write(attr, line, frozenset(self._guards)))

    def visit_Assign(self, node):  # noqa: N802
        for t in node.targets:
            for el in ast.walk(t) if isinstance(
                    t, (ast.Tuple, ast.List)) else (t,):
                self._record_write(self._self_attr_of_target(el),
                                   node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node):  # noqa: N802
        self._record_write(self._self_attr_of_target(node.target),
                           node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node):  # noqa: N802
        if node.value is not None:
            self._record_write(self._self_attr_of_target(node.target),
                               node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node):  # noqa: N802
        for t in node.targets:
            self._record_write(self._self_attr_of_target(t), node.lineno)

    # -- calls ---------------------------------------------------------
    def visit_Call(self, node):  # noqa: N802
        f = node.func
        if isinstance(f, ast.Attribute):
            # self.m(...) — in-class call edge
            if isinstance(f.value, ast.Name) \
                    and f.value.id in self.self_names \
                    and f.attr in self.method_names:
                self.info.calls.append((f.attr, frozenset(self._guards)))
            # self.attr.append(...) — in-place mutation of self.attr
            elif f.attr in MUTATORS:
                self._record_write(self._self_attr_of_target(f.value),
                                   node.lineno)
        self.generic_visit(node)


def _scan(body_owner, self_names: set[str],
          method_names: set[str]) -> _MethodInfo:
    sc = _BodyScanner(self_names, method_names)
    sc.info.name = body_owner.name
    for st in body_owner.body:
        sc.visit(st)
    return sc.info


def _self_aliases(method: ast.FunctionDef, self_name: str) -> set[str]:
    """Names bound to self inside a method (``outer = self``) — the
    nested-request-handler closure idiom."""
    out = {self_name}
    for node in ast.walk(method):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == self_name:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _inherited_guards(entries: dict[str, frozenset],
                      infos: dict[str, _MethodInfo]) -> dict[str, frozenset]:
    """Worklist fixed point: guard set guaranteed held whenever each
    reachable method runs on this side = intersection over call paths of
    (caller's guarantee ∪ call-site guards). Monotone decreasing."""
    state: dict[str, frozenset] = dict(entries)
    work = list(entries)
    while work:
        m = work.pop()
        base = state[m]
        for callee, site in infos.get(m, _MethodInfo(m)).calls:
            cand = base | site
            cur = state.get(callee)
            new = cand if cur is None else (cur & cand)
            if cur is None or new != cur:
                state[callee] = new
                work.append(callee)
    return state


def _nested_defs(method: ast.FunctionDef):
    """Directly nested FunctionDefs and ClassDefs (recursively, so a
    handler class inside a with-block is still found)."""
    funcs: dict[str, ast.FunctionDef] = {}
    classes: list[ast.ClassDef] = []
    stack = list(method.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs[node.name] = node
            continue  # do not look inside nested funcs for more
        if isinstance(node, ast.ClassDef):
            classes.append(node)
            continue
        stack.extend(ast.iter_child_nodes(node))
    return funcs, classes


def _analyze_class(sf, cls: ast.ClassDef) -> list[Finding]:
    methods = {n.name: n for n in cls.body
               if isinstance(n, ast.FunctionDef)}
    method_names = set(methods)

    # ---- find thread-side code ---------------------------------------
    #: entry method names spawned via Thread(target=self.m)
    entry_methods: set[str] = set()
    #: (nested function node, self-alias names) spawned via
    #: Thread(target=nested)
    nested_entries: list[tuple[ast.FunctionDef, set[str]]] = []
    #: request-handler classes: (handler ClassDef, outer-alias names);
    #: these run one thread PER CONNECTION — self-concurrent
    handler_classes: list[tuple[ast.ClassDef, set[str]]] = []

    for m in methods.values():
        if not m.args.args:
            continue
        self_name = m.args.args[0].arg
        aliases = _self_aliases(m, self_name)
        funcs, classes = _nested_defs(m)
        for node in ast.walk(m):
            if isinstance(node, ast.Call) and _is_thread_ctor(node):
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    tgt = kw.value
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id in aliases \
                            and tgt.attr in method_names:
                        entry_methods.add(tgt.attr)
                    elif isinstance(tgt, ast.Name) and tgt.id in funcs:
                        nested_entries.append((funcs[tgt.id], aliases))
        for nested_cls in classes:
            if any("RequestHandler" in (_dotted(b) or "")
                   for b in nested_cls.bases):
                handler_classes.append((nested_cls, aliases - {self_name}))

    if not (entry_methods or nested_entries or handler_classes):
        return []

    # ---- per-method write/call info ----------------------------------
    infos: dict[str, _MethodInfo] = {}
    for name, m in methods.items():
        if not m.args.args:
            infos[name] = _MethodInfo(name)
            continue
        infos[name] = _scan(m, {m.args.args[0].arg}, method_names)

    # ---- thread side -------------------------------------------------
    thread_writes: dict[str, list[tuple[_Write, bool]]] = {}
    concurrent_attrs: set[str] = set()

    def _fold_side(side_infos, inherited, concurrent, into):
        for name, info in side_infos.items():
            inh = inherited.get(name)
            if inh is None:
                continue
            for w in info.writes:
                eff = _Write(w.attr, w.line, w.guards | inh)
                into.setdefault(w.attr, []).append((eff, concurrent))
                if concurrent:
                    concurrent_attrs.add(w.attr)

    # entry methods + everything they reach
    inh_thread = _inherited_guards(
        {m: frozenset() for m in entry_methods}, infos)
    _fold_side({n: infos[n] for n in inh_thread if n in infos},
               inh_thread, False, thread_writes)
    # nested thread-target functions (scan with the enclosing self names)
    for idx, (fn, aliases) in enumerate(nested_entries):
        info = _scan(fn, aliases, method_names)
        key = f"<nested:{fn.name}:{idx}>"
        infos[key] = info
        inh = _inherited_guards({key: frozenset()}, infos)
        _fold_side({n: infos[n] for n in inh if n in infos},
                   inh, False, thread_writes)
    # request-handler classes: concurrent with themselves
    for idx, (hcls, outer_aliases) in enumerate(handler_classes):
        if not outer_aliases:
            continue
        hentries = {}
        for hm in hcls.body:
            if isinstance(hm, ast.FunctionDef):
                key = f"<handler:{hcls.name}.{hm.name}:{idx}>"
                infos[key] = _scan(hm, set(outer_aliases), method_names)
                hentries[key] = frozenset()
        inh = _inherited_guards(hentries, infos)
        _fold_side({n: infos[n] for n in inh if n in infos},
                   inh, True, thread_writes)

    # ---- main side ---------------------------------------------------
    # entries: public methods (incl. the dunder protocol surface), plus
    # private methods no in-class caller reaches (could be called from
    # outside). __init__ runs before any thread exists — excluded.
    called_by_someone = {callee for info in infos.values()
                         for callee, _ in info.calls}
    main_entries = {}
    for name in methods:
        if name == "__init__" or name in entry_methods:
            # __init__ runs before any thread exists; a thread-entry
            # method is the thread's code, not a main-side surface
            continue
        public = not name.startswith("_") or name in (
            "__call__", "__enter__", "__exit__", "__iter__", "__next__")
        if public or name not in called_by_someone:
            main_entries[name] = frozenset()
    inh_main = _inherited_guards(main_entries, infos)
    main_writes: dict[str, list[tuple[_Write, bool]]] = {}
    _fold_side({n: infos[n] for n in inh_main if n in infos},
               inh_main, False, main_writes)

    # ---- verdicts ----------------------------------------------------
    out: list[Finding] = []
    for attr in sorted(set(thread_writes) | set(main_writes)):
        tw = thread_writes.get(attr, [])
        mw = main_writes.get(attr, [])
        all_writes = [w for w, _c in tw + mw]
        common = None
        for w in all_writes:
            common = w.guards if common is None else (common & w.guards)
        guarded_everywhere = bool(common)
        both_sides = bool(tw) and bool(mw)
        concurrent_unguarded = attr in concurrent_attrs and any(
            not w.guards for w, c in tw if c)
        if (both_sides and not guarded_everywhere) or concurrent_unguarded:
            bad = next((w for w in all_writes if not w.guards),
                       all_writes[0])
            sides = ("handler-thread (self-concurrent)"
                     if concurrent_unguarded and not both_sides else
                     "thread and main")
            out.append(Finding(
                rule="race", path=sf.path, line=bad.line,
                symbol=f"{cls.name}.{attr}",
                message=(
                    f"written from {sides} without a common lock guard "
                    f"on every write path (thread writes: "
                    f"{sorted({w.line for w, _ in tw})}, main writes: "
                    f"{sorted({w.line for w, _ in mw})}) — guard every "
                    f"write with the same 'with self._lock', or suppress "
                    f"with a justification if the tolerance is "
                    f"documented")))
    return out


def _thread_name_findings(sf) -> list[Finding]:
    out = []
    for node in ast.walk(sf.tree):
        if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
            continue
        name_kw = next((kw for kw in node.keywords if kw.arg == "name"),
                       None)
        if name_kw is None:
            out.append(Finding(
                rule="thread-name", path=sf.path, line=node.lineno,
                symbol="Thread",
                message='anonymous thread in the serve stack — spawn '
                        'with name="rtap-<module>-<role>" so leak '
                        'fixtures and stuck-session triage can '
                        'attribute it'))
        elif isinstance(name_kw.value, ast.Constant) \
                and isinstance(name_kw.value.value, str) \
                and not name_kw.value.value.startswith("rtap-"):
            out.append(Finding(
                rule="thread-name", path=sf.path, line=node.lineno,
                symbol=f"Thread:{name_kw.value.value}",
                message=f'thread name "{name_kw.value.value}" does not '
                        'follow the rtap-<module>-<role> convention'))
    return out


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    for sf in ctx.files_under(*SCOPE):
        if sf.tree is None:
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                out.extend(_analyze_class(sf, node))
        out.extend(_thread_name_findings(sf))
    return out
