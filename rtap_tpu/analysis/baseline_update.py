"""``--update-baseline``: mechanical baseline maintenance, zero new whys.

The baseline (``analysis_baseline.json``) is justification storage —
every entry carries a reviewed ``why``. Its maintenance chores are
mechanical, though, and doing them by hand invites exactly the errors
the file exists to prevent:

* a symbol MOVED (file rename, class rename) leaves a stale entry plus
  a new finding — the why is still valid, only the key changed;
* a fixed finding leaves a stale entry that should be deleted;
* a genuinely new finding must NOT be baselined mechanically — a
  why-less entry is a gate failure by design, and this tool refuses to
  mint one.

:func:`update_baseline` runs a full cold analysis and rewrites the
file: stale entries whose ``(rule, symbol)`` reappears under exactly
one new path (or whose ``(rule, path)`` reappears under exactly one
new symbol) are RE-KEYED in place, keeping their why verbatim;
remaining stale entries are dropped; remaining unmatched findings are
reported and left failing (write a why by hand — inline suppression or
baseline entry — or fix the code). Ambiguous moves (two candidates)
are left alone rather than guessed.
"""

from __future__ import annotations

import json
import os

from rtap_tpu.analysis.core import (
    BASELINE_NAME,
    Baseline,
    Finding,
    run_analysis,
)

__all__ = ["update_baseline"]


def _symbol_tail(symbol: str) -> str | None:
    """The rename-stable part of a symbol: ``f:except Exception`` ->
    ``except Exception``, ``Racy.n`` -> ``n``; None when the symbol has
    no separator (nothing survives a rename, so nothing to match on)."""
    for sep in (":", "."):
        if sep in symbol:
            return symbol.split(sep, 1)[1]
    return None


def _rekey(stale: list[dict], findings: list[Finding],
           existing_paths: set[str]) -> tuple[
        list[tuple[dict, Finding]], list[dict], list[Finding]]:
    """Match stale entries to new findings, conservatively:

    * round 1 — file move: identical (rule, symbol) under a new path,
      and ONLY when the entry's old path no longer exists in the tree
      (if the old file is still there, the same-named finding
      elsewhere is more likely a new, unrelated site than a move);
    * round 2 — container rename: same (rule, path), same symbol TAIL
      (``f:except Exception`` → ``g:except Exception``), unique on
      both sides.

    Every surviving ambiguity is refused, not guessed — and re-keys
    are printed by the CLI and land in the committed baseline's diff,
    so a reviewer sees exactly which why moved where.
    -> (moves, leftover_stale, leftover_findings)."""
    moves: list[tuple[dict, Finding]] = []
    stale = list(stale)
    findings = list(findings)

    def match_round(keyer, eligible):
        nonlocal stale
        by_key: dict[tuple, list[Finding]] = {}
        for f in findings:
            k = keyer(f.rule, f.path, f.symbol)
            if k is not None:
                by_key.setdefault(k, []).append(f)
        still_stale = []
        for e in stale:
            k = keyer(e["rule"], e["path"], e["symbol"]) \
                if eligible(e) else None
            cands = by_key.get(k, []) if k is not None else []
            if len(cands) == 1 and cands[0] in findings:
                moves.append((e, cands[0]))
                findings.remove(cands[0])
            else:
                still_stale.append(e)
        stale = still_stale

    match_round(lambda rule, path, symbol: (rule, symbol),
                eligible=lambda e: e["path"] not in existing_paths)
    match_round(lambda rule, path, symbol:
                (rule, path, _symbol_tail(symbol))
                if _symbol_tail(symbol) is not None else None,
                eligible=lambda e: True)
    return moves, stale, findings


def update_baseline(root: str, baseline_path: str | None = None) -> dict:
    """Rewrite the baseline against a fresh cold run. Returns a summary
    dict: ``rekeyed`` [(old_key, new_key)], ``dropped`` [keys],
    ``unmatched`` [keys] (new findings this tool REFUSED to baseline),
    ``format_errors`` (why-less/malformed entries, left untouched for a
    human), and ``wrote`` (whether the file changed)."""
    baseline_path = baseline_path or os.path.join(root, BASELINE_NAME)
    baseline = Baseline.load(baseline_path)
    from rtap_tpu.analysis.core import AnalysisContext, discover_files

    files = discover_files(root)
    ctx = AnalysisContext(root=root, files=files)
    report = run_analysis(root, baseline=baseline, ctx=ctx)

    moves, leftover_stale, leftover_findings = _rekey(
        report.stale_baseline, report.findings,
        existing_paths={f.path for f in files})

    entries = list(baseline.entries)
    key_of = {id(e): (e.get("rule"), e.get("path"), e.get("symbol"))
              for e in entries}
    rekeyed, dropped = [], []
    drop_ids = set()
    for e, f in moves:
        old = key_of[id(e)]
        e["path"], e["symbol"] = f.path, f.symbol
        rekeyed.append((old, f.key()))
    for e in leftover_stale:
        drop_ids.add(id(e))
        dropped.append(key_of[id(e)])
    new_entries = [e for e in entries if id(e) not in drop_ids]

    wrote = bool(rekeyed or dropped)
    if wrote:
        try:
            with open(baseline_path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            data = {}
        if not isinstance(data, dict):
            data = {}
        data["entries"] = new_entries
        tmp = f"{baseline_path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, baseline_path)

    return {
        "rekeyed": rekeyed,
        "dropped": dropped,
        "unmatched": [f.key() for f in leftover_findings],
        "format_errors": list(baseline.format_errors),
        "wrote": wrote,
    }
