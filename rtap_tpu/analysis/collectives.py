"""Collective discipline: the chunk step stays collective-free, forever.

Rule ``collective-discipline`` (ISSUE 15) — the entire ROADMAP-1
scale-out story rests on one measured property: ``sharded_chunk_step``
is collective-free (SCALING.md — per-stream state never couples across
the mesh, so XLA inserts zero cross-chip communication and scale-out is
linear by construction). That property is currently true by
inspection; this pass makes it a permanent gate: ``psum`` /
``all_gather`` / ``ppermute`` / ``shard_map`` and friends are BANNED
everywhere except declared mesh entry points — the functions that own
placement (``rtap_tpu/parallel/`` wholesale, any function calling the
parallel placement API, or an explicit ``# rtap: mesh-entry — why``).

A collective inside a chunk-scan body would not just be slow: it would
change the program's numerics per mesh shape and break the bit-exact
single-device ≡ sharded contract the parity tree pins. Finding symbol:
``<qual>:collective:<name>``.
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.kernels import dotted
from rtap_tpu.analysis.meshmodel import build_mesh_model, scopes_of

PASS_NAME = "collective-discipline"
PARTITION = "file"
RULES = {
    "collective-discipline": "cross-device collectives (psum/"
                             "all_gather/ppermute/shard_map/...) "
                             "outside declared mesh entry points — "
                             "pins sharded_chunk_step's collective-"
                             "free property",
}

#: the jax cross-device vocabulary (lax collectives + the spmd wrappers)
_COLLECTIVES = frozenset({
    "psum", "psum_scatter", "pmean", "pmax", "pmin", "pbroadcast",
    "all_gather", "all_to_all", "ppermute", "pshuffle", "pswapaxes",
    "axis_index", "shard_map", "pmap", "xmap", "pdot",
})

#: call roots that make a bare-looking collective name credible
_ROOTS = ("jax", "lax", "jnp", "pl", "shard_map")


def run(ctx: AnalysisContext) -> list[Finding]:
    model = build_mesh_model(ctx)
    out: list[Finding] = []
    for sf in ctx.files:
        if sf.tree is None or sf.path.startswith("rtap_tpu/parallel/"):
            continue   # the mesh module is the blessed home
        if not any(name in sf.text for name in _COLLECTIVES):
            continue   # text prefilter: collectives are rare by design
        for qual, nodes in scopes_of(sf):
            if model.is_entry(sf.path, qual):
                continue
            for node in nodes:
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d is None:
                    continue
                leaf = d.rsplit(".", 1)[-1]
                if leaf not in _COLLECTIVES:
                    continue
                root = d.split(".", 1)[0]
                if "." in d and root not in _ROOTS:
                    continue   # someone else's method named psum
                in_ops = sf.path.startswith("rtap_tpu/ops/")
                out.append(Finding(
                    rule="collective-discipline", path=sf.path,
                    line=node.lineno,
                    symbol=f"{qual}:collective:{leaf}",
                    message=(f"collective {leaf}() "
                             + ("inside the kernel surface — the chunk "
                                "step's collective-free property is a "
                                "measured scale-out contract (SCALING."
                                "md); per-stream state must never "
                                "couple across the mesh"
                                if in_ops else
                                "outside a declared mesh entry point — "
                                "placement and cross-shard reduction "
                                "belong to rtap_tpu/parallel/ or a "
                                "`# rtap: mesh-entry` function")
                             + "; if this site must own placement, "
                               "declare it `# rtap: mesh-entry — why`")))
    return out
