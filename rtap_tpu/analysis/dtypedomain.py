"""Dtype-domain discipline: quantized permanences and i32 keys never
mix silently.

Rule ``dtype-domain`` — the u16→u8 permanence migration (ROADMAP-3,
grounded in the low-precision-HTM results of PAPERS 1803.05131 /
1812.10730) is only safe while every piece of arithmetic knows which
grid it is on: a u8 quantum added to a u16 quantum is a value bug no
dtype system catches (both sides are "just ints" by the time XLA sees
them), and i32 key arithmetic (``cat * w + k``) wraps on device where
host i64 silently would not — the exact class PR 9's categorical
double-clamp fixed by hand.

Domains are DECLARED, not inferred — a small annotation table per file
(docs/ANALYSIS.md):

    # rtap: domain[perm=u16, syn_perm=u16, keys=i32-key]     (module-wide)
    buckets = ...  # rtap: domain[i32-key]                    (this binding)

Module-wide entries bind variable names AND ``state["<name>"]``
subscript keys; the trailing form binds that assignment's targets.
Valid domains: ``u8 | u16 | i32-key``. Three findings:

* ``<qual>:mix:<a>~<b>`` — a binary op whose operands carry DIFFERENT
  declared domains with no explicit ``astype`` widening at the site;
* ``<qual>:i32-wrap:<v>`` — multiplication of an ``i32-key`` value
  that is not clamp-protected (produced by ``jnp.clip``/``np.clip``
  somewhere in its chain) — the add in ``bucket + arange`` is fine,
  the multiply in ``cat * w`` is where a wild category id wraps;
* ``<qual>:undeclared:<dtype>`` — a literal cast onto a quantized grid
  (``astype(jnp.uint8 | uint16)``) over a value with no declared
  domain: the cast invents a domain the table never heard of.

Scope: ``rtap_tpu/ops/``, ``rtap_tpu/models/``, ``scripts/`` and
``bench.py`` (bench/eval scaffolding builds quantized state too).
An ``astype`` whose target dtype is non-literal (``dom.compute_dtype``)
is the sanctioned domain-polymorphic idiom (models/perm.py) and clears
the operand's domain rather than guessing one.
"""

from __future__ import annotations

import ast
import re

from rtap_tpu.analysis.core import AnalysisContext, Finding, SourceFile
from rtap_tpu.analysis.kernels import dotted, functions_in, \
    stmt_expr_nodes

PASS_NAME = "dtype-domain"
PARTITION = "file"
RULES = {
    "dtype-domain": "cross-domain arithmetic without a widening cast, "
                    "unclamped i32-key multiplication, or a quantized "
                    "cast onto an undeclared domain",
}

_DOMAINS = ("u8", "u16", "i32-key")

_MODULE_RE = re.compile(
    r"#\s*rtap:\s*domain\[([A-Za-z_][\w]*\s*=\s*[\w-]+"
    r"(?:\s*,\s*[A-Za-z_][\w]*\s*=\s*[\w-]+)*)\]")
_TRAILING_RE = re.compile(r"#\s*rtap:\s*domain\[([\w-]+)\]")

#: literal cast targets that land on a quantized grid
_GRID_DTYPES = {"uint8": "u8", "uint16": "u16"}

_SCOPES = ("rtap_tpu/ops/", "rtap_tpu/models/", "scripts/", "bench.py")


def file_domain_table(sf: SourceFile) -> tuple[dict[str, str],
                                               dict[int, str],
                                               list[Finding]]:
    """(module-wide name->domain, lineno->domain for trailing form,
    syntax findings for unknown domain tokens)."""
    table: dict[str, str] = {}
    trailing: dict[int, str] = {}
    bad: list[Finding] = []
    for i, line in enumerate(sf.lines, start=1):
        m = _MODULE_RE.search(line)
        if m:
            for pair in m.group(1).split(","):
                name, dom = (s.strip() for s in pair.split("="))
                if dom not in _DOMAINS:
                    bad.append(Finding(
                        rule="dtype-domain", path=sf.path, line=i,
                        symbol=f"domain-syntax:{name}",
                        message=f"unknown domain '{dom}' — valid: "
                                f"{', '.join(_DOMAINS)}"))
                else:
                    table[name] = dom
            continue
        m = _TRAILING_RE.search(line)
        if m:
            dom = m.group(1)
            if dom not in _DOMAINS:
                bad.append(Finding(
                    rule="dtype-domain", path=sf.path, line=i,
                    symbol="domain-syntax:trailing",
                    message=f"unknown domain '{dom}' — valid: "
                            f"{', '.join(_DOMAINS)}"))
            else:
                trailing[i] = dom
    return table, trailing, bad


def _astype_target(call: ast.Call) -> str | None:
    """'u8'/'u16'/'i32-key' for a literal astype target, '' for a
    non-literal (domain-polymorphic) one, None if not an astype."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "astype" and call.args):
        return None
    d = dotted(call.args[0])
    if d is None:
        return ""
    leaf = d.rsplit(".", 1)[-1]
    if leaf in _GRID_DTYPES:
        return _GRID_DTYPES[leaf]
    if leaf == "int32":
        return "i32-key"
    # int64 is the HOST's wrap-safe widening (the oracle idiom) — it
    # clears the key domain rather than entering it
    return ""


class _Expr:
    """Domain + clamp provenance of one expression."""

    __slots__ = ("domain", "clamped", "name")

    def __init__(self, domain=None, clamped=False, name=None):
        self.domain = domain
        self.clamped = clamped
        self.name = name


def _eval(node: ast.AST, names: dict[str, "_Expr"],
          table: dict[str, str]) -> "_Expr":
    """Bottom-up domain evaluation of one expression."""
    if isinstance(node, ast.Name):
        if node.id in names:
            e = names[node.id]
            return _Expr(e.domain, e.clamped, node.id)
        if node.id in table:
            return _Expr(table[node.id], False, node.id)
        return _Expr()
    if isinstance(node, ast.Subscript):
        # state["perm"]-style access adopts the key's declared domain
        if isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str) \
                and node.slice.value in table:
            return _Expr(table[node.slice.value], False,
                         node.slice.value)
        return _eval(node.value, names, table)
    if isinstance(node, ast.Call):
        t = _astype_target(node)
        if t is not None:
            inner = _eval(node.func.value, names, table)
            # explicit cast: re-domains (literal) or clears (dynamic)
            return _Expr(t or None, inner.clamped, inner.name)
        d = dotted(node.func)
        leaf = d.rsplit(".", 1)[-1] if d else None
        if leaf == "clip":
            # module form clip(x, lo, hi) vs method form x.clip(lo, hi)
            if d in ("jnp.clip", "np.clip", "numpy.clip",
                     "jax.numpy.clip") and node.args:
                inner = _eval(node.args[0], names, table)
            elif isinstance(node.func, ast.Attribute):
                inner = _eval(node.func.value, names, table)
            else:
                inner = _Expr()
            return _Expr(inner.domain, True, inner.name)
        if leaf in ("where", "round", "minimum", "maximum", "abs"):
            doms = [_eval(a, names, table) for a in node.args]
            for e in doms:
                if e.domain is not None:
                    return _Expr(e.domain,
                                 all(x.clamped or x.domain is None
                                     for x in doms), e.name)
        return _Expr()
    if isinstance(node, ast.BinOp):
        left = _eval(node.left, names, table)
        right = _eval(node.right, names, table)
        dom = left.domain or right.domain
        return _Expr(dom, left.clamped or right.clamped,
                     left.name or right.name)
    if isinstance(node, ast.UnaryOp):
        return _eval(node.operand, names, table)
    return _Expr()


def _own_statements(fn: ast.FunctionDef):
    """fn's statements in source order, recursing into compound
    statements but not nested defs (those get their own qualnames)."""
    def rec(body):
        for st in body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            yield st
            for attr in ("body", "orelse", "finalbody"):
                yield from rec(getattr(st, attr, []))
            for h in getattr(st, "handlers", []):
                yield from rec(h.body)

    yield from rec(fn.body)


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    for sf in ctx.files_under(*_SCOPES):
        if sf.tree is None:
            continue
        table, trailing, bad = file_domain_table(sf)
        out.extend(bad)
        for qual, fn in functions_in(sf.tree):
            names: dict[str, _Expr] = {}
            for st in _own_statements(fn):
                for node in stmt_expr_nodes(st):
                    # ---- mixes + unclamped key multiplies -----------
                    if isinstance(node, ast.BinOp):
                        _check_arith(
                            _eval(node.left, names, table),
                            _eval(node.right, names, table),
                            node.op, node.lineno, qual, sf, out)
                    # ---- casts onto undeclared quantized grids ------
                    elif isinstance(node, ast.Call):
                        t = _astype_target(node)
                        if t in ("u8", "u16") \
                                and trailing.get(node.lineno) != t:
                            inner = _eval(node.func.value, names, table)
                            if inner.domain is None:
                                out.append(Finding(
                                    rule="dtype-domain", path=sf.path,
                                    line=node.lineno,
                                    symbol=f"{qual}:undeclared:{t}",
                                    message=f"literal cast onto the "
                                            f"{t} grid over a value "
                                            "with no declared domain "
                                            "— add it to the file's "
                                            "`# rtap: domain[...]` "
                                            "table so mixes stay "
                                            "machine-checkable"))
                # in-place updates are arithmetic too: `perm += d`
                # is the permanence-update idiom the u16->u8 rail
                # exists for, and it never shows up as a BinOp
                if isinstance(st, ast.AugAssign):
                    left = _eval(st.target, names, table)
                    right = _eval(st.value, names, table)
                    _check_arith(left, right, st.op, st.lineno, qual,
                                 sf, out)
                    if isinstance(st.target, ast.Name):
                        names[st.target.id] = _Expr(
                            left.domain or right.domain,
                            left.clamped and right.clamped,
                            st.target.id)
                # ---- bind AFTER checking (RHS uses prior names) -----
                if isinstance(st, ast.Assign) and st.value is not None:
                    e = _eval(st.value, names, table)
                    decl = trailing.get(st.lineno)
                    if decl is not None:
                        e = _Expr(decl, e.clamped, e.name)
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            names[t.id] = e
    return out


def _check_arith(left: "_Expr", right: "_Expr", op: ast.operator,
                 lineno: int, qual: str, sf, out: list[Finding]) -> None:
    """The mix / i32-wrap judgment for one binary operation — shared by
    BinOp expressions and AugAssign statements."""
    if left.domain and right.domain and left.domain != right.domain:
        a, b = sorted((left.domain, right.domain))
        out.append(Finding(
            rule="dtype-domain", path=sf.path, line=lineno,
            symbol=f"{qual}:mix:{a}~{b}",
            message=f"arithmetic mixes domains {left.domain} and "
                    f"{right.domain} with no explicit widening cast — "
                    "quanta on different grids are different VALUES; "
                    "astype through the compute domain first "
                    "(models/perm.py)"))
    elif isinstance(op, ast.Mult):
        for side in (left, right):
            if side.domain == "i32-key" and not side.clamped:
                out.append(Finding(
                    rule="dtype-domain", path=sf.path, line=lineno,
                    symbol=f"{qual}:i32-wrap:{side.name or 'expr'}",
                    message="multiplying an unclamped i32-key value — "
                            "device i32 wraps where host i64 would "
                            "not (the PR 9 categorical class); clamp "
                            "to the key bound first"))
                break
