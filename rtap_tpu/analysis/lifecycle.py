"""Resource lifecycle: every owned thread/socket/shm/file can be torn
down.

Rule ``resource-lifecycle`` — a serve-stack class that stores a
``threading.Thread``/``Timer``, a socket, a ``SharedMemory`` segment,
or an ``open()`` file handle on ``self`` OWNS that resource, and its
teardown surface (``close()``/``__exit__``/``stop*()``/``shutdown()``)
must reach a matching release — ``join`` for threads (with a timeout:
an unbounded join turns one wedged thread into a wedged process, the
exact hang class the kill-9 soaks exist to rule out), ``close`` for
sockets/files, ``close``/``unlink`` for shm. The BinaryBatchSource
leak class (PR 7: handler sockets and the accept thread outliving
``close()``) is this pass's reason to exist; the conftest thread-leak
fixture catches leaks a test HAPPENS to exercise, this catches the
path that exists but is not wired.

Reachability is interprocedural within the class: the teardown entry
points are the methods named ``close``, ``shutdown``, ``stop``,
``__exit__``, ``__del__`` or starting with ``stop_``/``close_``, plus
everything they call (in-class call graph, worklist closure). A
release seen anywhere in that closure clears the attribute.

Out of scope by design: resources bound to locals (the ``with
socket.create_connection(...)`` idiom scopes them lexically — storing
on ``self`` is what creates an ownership obligation this pass can
check), and fire-and-forget ``Thread(...).start()`` expressions (the
races/thread-name passes already force those to be nameable; daemon
threads without state to flush are legal there).

Symbols are ``Class.attr`` (and ``Class.attr:unbounded-join`` for the
timeout variant) — line-insensitive for baselining.
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.program import (
    _self_attr_target,
    dotted,
    is_thread_ctor,
)

PASS_NAME = "resource-lifecycle"
#: findings depend only on one file's bytes -> the warm
#: cache may replay them per file (core.py partition contract)
PARTITION = "file"
RULES = {
    "resource-lifecycle": "class-owned thread/socket/shm/file with no "
                          "reachable release (join-with-timeout/close/"
                          "unlink) on the close()/__exit__ teardown "
                          "path",
}

SCOPE = ("rtap_tpu/service/", "rtap_tpu/obs/", "rtap_tpu/resilience/",
         "rtap_tpu/ingest/", "rtap_tpu/correlate/", "rtap_tpu/fleet/")

#: resource kind -> (constructor dotted-name suffixes, release method
#: names, human name)
_KINDS = {
    "thread": ((), ("join",), "thread"),
    "socket": (("socket.socket", "socket.create_connection",
                "create_connection", "socket.socketpair"),
               ("close", "shutdown", "detach"), "socket"),
    "shm": (("shared_memory.SharedMemory", "SharedMemory"),
            ("close", "unlink"), "shared-memory segment"),
    "file": (("open", "io.open", "os.fdopen", "gzip.open", "lzma.open"),
             ("close",), "file handle"),
}

#: teardown surface: these methods (plus their in-class call closure)
#: are where releases must live
_TEARDOWN_EXACT = ("close", "shutdown", "stop", "__exit__", "__del__")
_TEARDOWN_PREFIX = ("stop_", "close_")


def _kind_of_ctor(call: ast.Call) -> str | None:
    if is_thread_ctor(call):
        return "thread"
    d = dotted(call.func)
    if d is None:
        return None
    for kind, (ctors, _rel, _h) in _KINDS.items():
        if d in ctors:
            return kind
    return None


def _is_teardown(name: str) -> bool:
    return name in _TEARDOWN_EXACT \
        or any(name.startswith(p) for p in _TEARDOWN_PREFIX)


def _analyze_class(sf, cls: ast.ClassDef) -> list[Finding]:
    methods = {n.name: n for n in cls.body
               if isinstance(n, ast.FunctionDef)}

    #: attr -> (kind, line of the creating assignment)
    resources: dict[str, tuple[str, int]] = {}
    #: method -> set of in-class callees
    calls: dict[str, set[str]] = {m: set() for m in methods}
    #: method -> list of (attr, release method name, has-timeout)
    releases: dict[str, list[tuple[str, str, bool]]] = \
        {m: [] for m in methods}

    def _own_nodes(m):
        # skip nested function/class defs: a nested handler class is
        # analyzed as its own class (run() walks every ClassDef), and
        # its self is NOT this method's self
        stack = list(m.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    for mname, m in methods.items():
        if not m.args.args:
            continue
        self_name = m.args.args[0].arg
        for node in _own_nodes(m):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                kind = _kind_of_ctor(node.value)
                if kind is not None:
                    for t in node.targets:
                        attr = _self_attr_target(t, self_name)
                        if attr is not None and attr not in resources:
                            resources[attr] = (kind, node.lineno)
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not isinstance(f, ast.Attribute):
                continue
            if isinstance(f.value, ast.Name) \
                    and f.value.id == self_name \
                    and f.attr in methods:
                calls[mname].add(f.attr)
            recv_attr = None
            if isinstance(f.value, ast.Attribute) \
                    and isinstance(f.value.value, ast.Name) \
                    and f.value.value.id == self_name:
                recv_attr = f.value.attr
            if recv_attr is not None:
                def _bounded(v):
                    # an explicit None is the UNbounded spelling
                    return not (isinstance(v, ast.Constant)
                                and v.value is None)

                has_timeout = (
                    bool(node.args) and _bounded(node.args[0])) or any(
                    kw.arg == "timeout" and _bounded(kw.value)
                    for kw in node.keywords)
                releases[mname].append((recv_attr, f.attr, has_timeout))

    if not resources:
        return []

    # teardown closure: entry methods + everything they reach in-class
    entry = {m for m in methods if _is_teardown(m)}
    reach = set(entry)
    work = list(entry)
    while work:
        m = work.pop()
        for callee in calls.get(m, ()):
            if callee not in reach:
                reach.add(callee)
                work.append(callee)

    out: list[Finding] = []
    for attr in sorted(resources):
        kind, line = resources[attr]
        rel_names = _KINDS[kind][1]
        human = _KINDS[kind][2]
        hits = [(m, rel, to) for m in sorted(reach)
                for a, rel, to in releases.get(m, ())
                if a == attr and rel in rel_names]
        if not hits:
            if not entry:
                why = (f"{cls.name} has no teardown surface at all "
                       "(no close/stop/shutdown/__exit__)")
            else:
                why = (f"nothing reachable from "
                       f"{'/'.join(sorted(entry))} releases it")
            out.append(Finding(
                rule="resource-lifecycle", path=sf.path, line=line,
                symbol=f"{cls.name}.{attr}",
                message=(
                    f"{human} self.{attr} is created here but {why} — "
                    f"add a {'bounded join' if kind == 'thread' else rel_names[0]} "
                    "on the close()/__exit__ path (leaked "
                    f"{human}s are the BinaryBatchSource PR 7 bug "
                    "class)")))
        elif kind == "thread" and not any(to for _m, _r, to in hits):
            jm = sorted({m for m, _r, _to in hits})
            out.append(Finding(
                rule="resource-lifecycle", path=sf.path, line=line,
                symbol=f"{cls.name}.{attr}:unbounded-join",
                message=(
                    f"thread self.{attr} is joined in "
                    f"{', '.join(jm)} without a timeout — one wedged "
                    "thread wedges the whole teardown; join with a "
                    "bounded timeout and let the daemon flag cover "
                    "the remainder")))
    return out


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    for sf in ctx.files_under(*SCOPE):
        if sf.tree is None:
            continue
        # ast.walk, not tree.body: nested classes (the in-method
        # request-handler idiom) own per-connection resources too
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(_analyze_class(sf, node))
    return out
