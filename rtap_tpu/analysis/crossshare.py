"""Cross-object sharing: objects handed to another thread's world.

Rule ``cross-share`` — the races pass (PR 11) deliberately stopped at
the class boundary: it sees a class race with ITS OWN thread, but not
the ``live_loop``-plus-obs-HTTP pattern where one scope constructs an
object (``health = HealthTracker(...)``) and hands it BOTH to a
thread-running class (``ExpositionServer(health=health)`` — whose HTTP
handler threads read it) and to another consumer (``live_loop(...,
health=health)`` — the loop thread writes it). Those surfaces were
"audited by hand" in docs/ANALYSIS.md; this pass automates the audit
and retires the list.

Detection, in two halves over the whole-program model
(rtap_tpu/analysis/program.py):

1. **Sharing** — a local bound to a known-class constructor that is
   handed to two or more distinct consumers (constructor/function
   calls, or direct method use by the constructing scope), at least one
   of which is a thread-running class (spawns ``threading.Thread`` /
   subclasses a ``Threading*`` server — its handler/background threads
   will touch the object). Every such class is *cross-thread shared*.

2. **Verdict per attribute** — inside a shared class, a ``self.*``
   attribute that is MUTATED IN PLACE (``+=``, ``self.x[k] = v``,
   ``.append``/``.update``/…) outside ``__init__`` on a write path that
   does not hold a lock guard, while some OTHER method reads it, is
   flagged. Atomic REBINDS (``self.x = fresh``) are exempt: rebinding a
   fully-built dict/array is the serve stack's documented snapshot
   idiom (readers see old-or-new, never torn) — exactly the line the
   hand audits drew between HealthTracker's rebound scorecards (fine)
   and the ``Lease.set_meta`` in-place insert (the PR 8 bug). Guard
   inheritance is interprocedural within the class, same intersection
   semantics as the races pass: a helper reached both with and without
   the lock counts as unguarded.

Deliberate tolerances (single-writer diagnostic counters read torn —
the obs idiom) belong in ``analysis_baseline.json`` with a why; that is
the hand-audit list's retirement home, not a reason to weaken the pass.
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.program import build_program
from rtap_tpu.analysis.races import (
    GUARD_HINTS,
    MUTATORS,
    _inherited_guards,
    _MethodInfo,
    _Write,
)

PASS_NAME = "cross-share"
#: cross-file inputs -> all-or-nothing in the findings cache
PARTITION = "program"
RULES = {
    "cross-share": "object shared between a thread-running class and "
                   "another consumer has an attribute mutated in place "
                   "without a guard while other methods read it",
}

#: where shared objects get WIRED (constructors + the CLI) — the scan
#: scope for construction sites; the shared class itself may live
#: anywhere under rtap_tpu/
SCOPE = ("rtap_tpu/service/", "rtap_tpu/obs/", "rtap_tpu/resilience/",
         "rtap_tpu/ingest/", "rtap_tpu/correlate/", "rtap_tpu/fleet/",
         "rtap_tpu/__main__.py")


class _AttrScan(ast.NodeVisitor):
    """One method body: in-place mutations, reads, calls — with the
    lexical lock-guard stack (the races-pass discipline, pointed at
    reads as well as writes)."""

    def __init__(self, self_name: str, method_names: set[str]):
        self.self_name = self_name
        self.method_names = method_names
        self._guards: list[str] = []
        self.info = _MethodInfo(name="")
        self.reads: set[str] = set()

    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):  # noqa: N802
        pass

    def _guard_of(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == self.self_name \
                and any(h in expr.attr.lower() for h in GUARD_HINTS):
            return expr.attr
        return None

    def visit_With(self, node):  # noqa: N802
        names = [g for g in (self._guard_of(it.context_expr)
                             for it in node.items) if g is not None]
        self._guards.extend(names)
        for st in node.body:
            self.visit(st)
        if names:
            del self._guards[-len(names):]

    def _self_attr(self, node: ast.AST) -> str | None:
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == self.self_name:
            return node.attr
        return None

    def _mutation(self, attr: str | None, line: int) -> None:
        if attr is not None:
            self.info.writes.append(
                _Write(attr, line, frozenset(self._guards)))

    def visit_AugAssign(self, node):  # noqa: N802
        t = node.target
        self._mutation(self._self_attr(t), node.lineno)
        if isinstance(t, ast.Subscript):
            self._mutation(self._self_attr(t.value), node.lineno)
        self.visit(node.value)

    def visit_Assign(self, node):  # noqa: N802
        # ONLY subscript-stores are mutations; a plain rebind
        # (self.x = fresh) is the atomic snapshot idiom and exempt
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._mutation(self._self_attr(t.value), node.lineno)
        self.visit(node.value)

    def visit_Delete(self, node):  # noqa: N802
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                self._mutation(self._self_attr(t.value), node.lineno)

    def visit_Call(self, node):  # noqa: N802
        f = node.func
        if isinstance(f, ast.Attribute):
            attr = self._self_attr(f.value)
            if attr is not None and f.attr in MUTATORS:
                self._mutation(attr, node.lineno)
            elif isinstance(f.value, ast.Name) \
                    and f.value.id == self.self_name \
                    and f.attr in self.method_names:
                self.info.calls.append((f.attr, frozenset(self._guards)))
        self.generic_visit(node)

    def visit_Attribute(self, node):  # noqa: N802
        if isinstance(node.ctx, ast.Load):
            attr = self._self_attr(node)
            if attr is not None:
                self.reads.add(attr)
        self.generic_visit(node)


def _shared_classes(prog, scope_paths):
    """class name -> one representative construction site proving the
    instance crosses a thread boundary."""
    out: dict[str, tuple[str, int, str]] = {}
    for rec in prog.constructed:
        if rec.path not in scope_paths:
            continue
        consumers = set(rec.consumers)
        if rec.direct_calls:
            consumers.add(f"<{rec.func_qual}>")
        if len(consumers) < 2:
            continue
        threaded = any(
            (ci := prog.classes.get(c.rsplit(".", 1)[-1])) is not None
            and ci.spawns_thread
            for c in rec.consumers)
        if threaded and rec.cls not in out:
            out[rec.cls] = (rec.path, rec.line, rec.var)
    return out


def run(ctx: AnalysisContext) -> list[Finding]:
    prog = build_program(ctx)
    scope_paths = {sf.path for sf in ctx.files_under(*SCOPE)}
    shared = _shared_classes(prog, scope_paths)

    out: list[Finding] = []
    for cname in sorted(shared):
        ci = prog.classes.get(cname)
        if ci is None:
            continue
        where_path, where_line, var = shared[cname]
        # a class that spawns its own threads is the races pass's beat;
        # double-reporting the same attrs under two rules helps nobody
        if ci.spawns_thread:
            continue
        method_names = set(ci.methods)
        scans: dict[str, _AttrScan] = {}
        infos: dict[str, _MethodInfo] = {}
        for mname, m in ci.methods.items():
            if not m.args.args:
                continue
            sc = _AttrScan(m.args.args[0].arg, method_names)
            sc.info.name = mname
            for st in m.body:
                sc.visit(st)
            scans[mname] = sc
            infos[mname] = sc.info
        # interprocedural guard inheritance, races-pass entry logic:
        # entries are the PUBLIC surface (either side may call in) plus
        # private methods no in-class caller reaches; a private helper
        # whose every call site holds the lock inherits it
        # (intersection over paths)
        called = {callee for info in infos.values()
                  for callee, _g in info.calls}
        entries = {}
        for n in scans:
            if n == "__init__":
                continue
            public = not n.startswith("_") or n in (
                "__call__", "__enter__", "__exit__", "__iter__",
                "__next__")
            if public or n not in called:
                entries[n] = frozenset()
        inherited = _inherited_guards(entries, infos)
        writers: dict[str, list[tuple[str, _Write, frozenset]]] = {}
        readers: dict[str, set[str]] = {}
        for mname, sc in scans.items():
            if mname == "__init__" or mname not in inherited:
                # not reachable from the post-construction surface:
                # construction-time code, not a shared-state side
                continue
            inh = inherited[mname]
            for w in sc.info.writes:
                writers.setdefault(w.attr, []).append(
                    (mname, w, w.guards | inh))
            for a in sc.reads:
                readers.setdefault(a, set()).add(mname)
        for attr in sorted(writers):
            wlist = writers[attr]
            common = None
            for _m, _w, g in wlist:
                common = g if common is None else (common & g)
            if common:
                continue  # every mutation path holds a common guard
            writing = {m for m, _w, _g in wlist}
            other_readers = sorted(readers.get(attr, set()) - writing)
            if not other_readers:
                continue  # nobody on the other side looks at it
            bad = next((w for _m, w, g in wlist if not g), wlist[0][1])
            out.append(Finding(
                rule="cross-share", path=ci.path, line=bad.line,
                symbol=f"{cname}.{attr}",
                message=(
                    f"{cname} instances are shared across threads "
                    f"(constructed as '{var}' at {where_path}:"
                    f"{where_line} and handed to a thread-running "
                    f"consumer); '{attr}' is mutated in place without "
                    f"a common guard while {', '.join(other_readers)} "
                    "read(s) it — rebind atomically, guard both sides, "
                    "or expose a locked snapshot")))
    return out
