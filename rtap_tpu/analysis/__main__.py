"""CLI: ``python -m rtap_tpu.analysis [--json] [--sarif PATH]
[--rules ...] [--no-cache]``.

Exit codes: 0 = zero unsuppressed findings (the gate), 1 = findings or
baseline format errors, 2 = usage error. The human report goes to
stderr; ``--json`` prints exactly one JSON artifact line to stdout (the
soak/hw_session archival surface — same one-JSON-line stdout contract
as bench.py), so both can be combined in one invocation. ``--sarif``
writes a SARIF 2.1.0 log to a FILE (never stdout — the one-line
contract stays intact) for CI/editor rendering.

Full runs are served from the per-file content-hash findings cache
(``<root>/.rtap_lint_cache.json``, gitignored): any file edit, add,
delete, docs change, baseline change, or analyzer change re-runs cold;
an untouched tree replays the identical report sub-second. ``--rules``
subsets bypass the cache entirely, ``--no-cache`` forces a cold run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from rtap_tpu.analysis import ALL_RULES
from rtap_tpu.analysis.core import (
    BASELINE_NAME,
    Baseline,
    render_human,
    run_analysis,
    run_analysis_cached,
)


def _default_root() -> str:
    """The repo root: the cwd when it holds rtap_tpu/, else the package's
    grandparent (so the module runs from anywhere inside the checkout)."""
    cwd = os.getcwd()
    if os.path.isdir(os.path.join(cwd, "rtap_tpu")):
        return cwd
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rtap_tpu.analysis",
        description="rtap-lint: AST-based invariant analysis "
                    "(docs/ANALYSIS.md)")
    ap.add_argument("--root", default=None,
                    help="repo root to analyze (default: auto-detected)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON artifact line on stdout "
                         "(findings, counts, timings)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{BASELINE_NAME})")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all; "
                         "subsets bypass the findings cache)")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write a SARIF 2.1.0 log to PATH (CI/"
                         "editor rendering; stdout keeps the one-line "
                         "--json contract)")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write the findings cache "
                         "(forces a cold run)")
    ap.add_argument("--cache-path", default=None, metavar="PATH",
                    help="findings cache location (default: "
                         "<root>/.rtap_lint_cache.json)")
    ap.add_argument("--list-passes", action="store_true",
                    help="list rule ids + descriptions and exit")
    ap.add_argument("--update-baseline", action="store_true",
                    help="mechanical baseline maintenance: re-key moved "
                         "symbols (whys preserved verbatim), drop stale "
                         "entries; REFUSES to mint entries for new "
                         "findings (a why-less entry is a gate failure "
                         "by design)")
    args = ap.parse_args(argv)

    if args.list_passes:
        for rid, desc in sorted(ALL_RULES.items()):
            print(f"{rid:18s} {desc}", file=sys.stderr)
        return 0

    root = args.root or _default_root()
    if not os.path.isdir(os.path.join(root, "rtap_tpu")):
        print(f"rtap-lint: {root} does not look like the repo root "
              "(no rtap_tpu/)", file=sys.stderr)
        return 2
    rules = None
    if args.rules:
        rules = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = rules - set(ALL_RULES) - {"parse-error"}
        if unknown:
            print(f"rtap-lint: unknown rule(s): {sorted(unknown)} "
                  f"(known: {sorted(ALL_RULES)})", file=sys.stderr)
            return 2
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    if args.update_baseline:
        from rtap_tpu.analysis.baseline_update import update_baseline

        summary = update_baseline(root, baseline_path=baseline_path)
        for old, new in summary["rekeyed"]:
            print(f"rekeyed: {':'.join(old)} -> {':'.join(new)}",
                  file=sys.stderr)
        for key in summary["dropped"]:
            print(f"dropped stale: {':'.join(key)}", file=sys.stderr)
        for key in summary["unmatched"]:
            print(f"NOT baselined (write the why yourself): "
                  f"{':'.join(key)}", file=sys.stderr)
        for e in summary["format_errors"]:
            print(f"left malformed entry for a human: {e}",
                  file=sys.stderr)
        print(f"--update-baseline: {len(summary['rekeyed'])} rekeyed, "
              f"{len(summary['dropped'])} dropped, "
              f"{len(summary['unmatched'])} refused, "
              f"{'wrote' if summary['wrote'] else 'no change to'} "
              f"{baseline_path}", file=sys.stderr)
        return 1 if summary["unmatched"] or summary["format_errors"] \
            else 0
    if rules is None and not args.no_cache:
        report = run_analysis_cached(root, baseline_path=baseline_path,
                                     cache_path=args.cache_path)
    else:
        report = run_analysis(root, baseline=Baseline.load(baseline_path),
                              rules=rules)
    print(render_human(report), file=sys.stderr)
    if args.json:
        print(json.dumps(report.to_dict()))
    if args.sarif:
        from rtap_tpu.analysis.sarif import to_sarif

        with open(args.sarif, "w", encoding="utf-8") as fh:
            json.dump(to_sarif(report), fh, indent=2)
            fh.write("\n")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
