"""Silent-exception discipline in the serve stack.

Rule ``except-silent`` — every ``except`` in ``service/``, ``obs/``,
``resilience/``, ``ingest/``, ``correlate/`` must DO something:
re-raise, log, bump an obs instrument, or at minimum bind an outcome
(assign a fallback, return, continue/break). A handler whose body is
nothing but ``pass`` swallows the fault with no trace — at 1M streams
that is an invisible outage, and the incident stream exists precisely
so faults narrate themselves.

One narrow carve-out: the universal cleanup idiom

    try:
        sock.close()
    except OSError:
        pass

is allowed when (a) the handler catches only OSError-family exceptions
and (b) the try body is a single teardown call (``close``/``shutdown``/
``unlink``/``terminate``/``kill``) — a failing close has no outcome
worth narrating. Everything else bare needs a suppression with a
justification or a baseline entry (grandfathered sites carry their
"why" there; see docs/ANALYSIS.md).

Symbols are ``<qualname>:except <types>[#n]`` — stable under line
drift, disambiguated by ordinal when one function has several identical
handlers.
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding

PASS_NAME = "excepts"
#: findings depend only on one file's bytes -> the warm
#: cache may replay them per file (core.py partition contract)
PARTITION = "file"
RULES = {
    "except-silent": "except handler in the serve stack whose body is "
                     "a bare pass (no re-raise, log, instrument bump, "
                     "or bound outcome)",
}

SCOPE = ("rtap_tpu/service/", "rtap_tpu/obs/", "rtap_tpu/resilience/",
         "rtap_tpu/ingest/", "rtap_tpu/correlate/", "rtap_tpu/fleet/")

#: teardown calls whose failure has no narratable outcome
_CLEANUP_CALLS = frozenset({
    "close", "shutdown", "unlink", "terminate", "kill", "server_close",
})

#: exception names admissible for the cleanup carve-out
_OS_ERRORS = frozenset({
    "OSError", "IOError", "ConnectionError", "ConnectionResetError",
    "BrokenPipeError", "FileNotFoundError", "TimeoutError",
    "socket.timeout", "socket.error",
})


def _inert(body: list[ast.stmt]) -> bool:
    """True when the handler body does nothing observable."""
    for st in body:
        if isinstance(st, ast.Pass):
            continue
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Constant):
            continue  # docstring/ellipsis
        return False
    return True


def _exc_names(h: ast.ExceptHandler) -> list[str]:
    if h.type is None:
        return ["<bare>"]
    nodes = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    out = []
    for n in nodes:
        try:
            out.append(ast.unparse(n))
        except Exception:  # pragma: no cover — unparse is total on exprs
            out.append("?")
    return out


def _cleanup_shaped(try_node: ast.Try, h: ast.ExceptHandler) -> bool:
    if not all(n in _OS_ERRORS for n in _exc_names(h)):
        return False
    if len(try_node.body) != 1:
        return False
    st = try_node.body[0]
    return (isinstance(st, ast.Expr) and isinstance(st.value, ast.Call)
            and isinstance(st.value.func, ast.Attribute)
            and st.value.func.attr in _CLEANUP_CALLS)


def _qualname_index(tree: ast.AST) -> dict[int, str]:
    """lineno -> enclosing function qualname (best-effort, for symbols)."""
    spans: list[tuple[int, int, str]] = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                end = getattr(child, "end_lineno", child.lineno)
                spans.append((child.lineno, end, q))
                walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return spans


def _qual_of(spans, line: int) -> str:
    """Innermost enclosing function qualname (smallest covering span)."""
    best = "<module>"
    best_size = None
    for lo, hi, q in spans:
        if lo <= line <= hi:
            size = hi - lo
            if best_size is None or size < best_size:
                best, best_size = q, size
    return best


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    for sf in ctx.files_under(*SCOPE):
        if sf.tree is None:
            continue
        index = _qualname_index(sf.tree)
        seen_symbols: dict[str, int] = {}
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Try):
                continue
            for h in node.handlers:
                if not _inert(h.body):
                    continue
                if _cleanup_shaped(node, h):
                    continue
                qual = _qual_of(index, h.lineno)
                base = f"{qual}:except {', '.join(_exc_names(h))}"
                n = seen_symbols.get(base, 0)
                seen_symbols[base] = n + 1
                symbol = base if n == 0 else f"{base}#{n + 1}"
                out.append(Finding(
                    rule="except-silent", path=sf.path, line=h.lineno,
                    symbol=symbol,
                    message="bare-pass handler in the serve stack — "
                            "re-raise, log, bump an obs instrument, or "
                            "bind a fallback outcome; if the swallow is "
                            "deliberate, suppress with the reason"))
    return out
