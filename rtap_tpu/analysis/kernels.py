"""The device-kernel model the v3 passes share (ISSUE 14).

The v2 program model (``program.py``) understands classes, locks, and
threads — the HOST side. The paper's bit-exactness story, though, rests
on DEVICE-side contracts no test fully covers: every ``ops/`` kernel has
a byte-identical oracle twin, donated buffers die at dispatch, static
jit arguments stay hashable, and the quantized permanence domains never
mix without a widening cast. This module builds the one model those
passes share, once per run, memoized on the context:

* **kernel discovery**: every top-level function in ``rtap_tpu/ops/``
  with a *traced* body (``jnp``/``lax``/``pl`` usage that is a call or a
  non-dtype attribute — ``jnp.int8`` alone is a dtype table, not a
  trace) — public ones form the twin-parity surface;
* **jit-wrapper extraction**: ``@jax.jit`` / ``@partial(jax.jit, ...)``
  decorators anywhere in the analysis surface, with their
  ``static_argnames``/``static_argnums``/``donate_argnums`` and the
  donated *param names* resolved against the signature — the
  donation-discipline and static-hash passes' ground truth;
* **twin registry**: each public ops kernel resolved to its oracle twin
  by name pairing — exact name, ``<name>_np``/``<name>_host`` host-twin
  suffixes, a stripped ``_device`` suffix — against the oracle scope
  (``rtap_tpu/models/`` + ``rtap_tpu/utils/hashing.py``) and same-file
  host twins, or by an explicit annotation::

      # rtap: twin[TMOracle] — megakernel twin of the default TM path

  on the ``def`` (or its decorator) line. Name-paired function twins
  must agree on positional arity (the "compatible signature" check);
  an annotated pairing is the reviewed assertion and only has to
  resolve.

Everything is pure AST — no jax import, same discipline as the rest of
the analyzer.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from rtap_tpu.analysis.core import AnalysisContext, SourceFile

__all__ = [
    "Kernel",
    "KernelModel",
    "Wrapper",
    "build_kernel_model",
    "dotted",
    "is_traced",
    "own_body_nodes",
    "stmt_expr_nodes",
    "twin_annotation",
]

#: the twin-annotation grammar (docs/ANALYSIS.md): target is an oracle
#: symbol (function, class, or Class.method) or a same-file host twin
_TWIN_RE = re.compile(r"#\s*rtap:\s*twin\[([A-Za-z_][\w.]*)\]")

#: files searched for oracle twins, by prefix (the host/semantic side
#: of every device kernel lives here)
ORACLE_SCOPE = ("rtap_tpu/models/", "rtap_tpu/utils/hashing.py")

#: jnp/lax attributes that are dtype/constant tables, not traced compute
#: — a function whose only jnp usage is ``jnp.int8`` selects a dtype,
#: it does not trace
_DTYPE_ATTRS = frozenset({
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bfloat16", "bool_",
    "ndarray", "dtype", "nan", "inf", "pi", "newaxis",
})

#: names whose calls/attributes mean "this body traces"
_TRACE_ROOTS = ("jnp", "lax", "pl", "pltpu")


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class Wrapper:
    """One jit-wrapped function: the dispatch boundary the donation and
    static-hash passes reason about."""

    name: str
    path: str
    line: int
    node: ast.FunctionDef
    params: list[str] = field(default_factory=list)     # positional
    kwonly: list[str] = field(default_factory=list)
    static_argnames: set[str] = field(default_factory=set)
    static_argnums: set[int] = field(default_factory=set)
    donate_argnums: set[int] = field(default_factory=set)
    #: defined inside another function (a factory-local wrapper like
    #: _sharded_chunk_fn's `run`): its NAME is meaningless outside the
    #: defining file, so donation call-site matching stays local
    nested: bool = False

    @property
    def donate_params(self) -> set[str]:
        return {self.params[i] for i in self.donate_argnums
                if 0 <= i < len(self.params)}


@dataclass
class Kernel:
    """One top-level traced function in ops/ (public ones are the
    twin-parity surface)."""

    name: str
    path: str
    line: int
    node: ast.FunctionDef
    arity: int                  # positional params (the signature check)
    public: bool
    twin_decl: str | None = None   # rtap: twin[...] target, if any


@dataclass
class KernelModel:
    kernels: list[Kernel] = field(default_factory=list)
    #: EVERY jit wrapper in the surface, in deterministic discovery
    #: order — a list, not a by-name dict, so a same-named wrapper in
    #: another file (the nested-factory `run` idiom) is still checked
    #: by static-hash and visible to donation in its own file
    wrappers: list[Wrapper] = field(default_factory=list)
    #: oracle scope symbols: name -> positional arity for functions,
    #: None for classes (a class twin has no single arity)
    oracle: dict[str, int | None] = field(default_factory=dict)
    #: per-ops-file function name sets (same-file host-twin lookup)
    ops_functions: dict[str, dict[str, int]] = field(default_factory=dict)

    def resolve_twin(self, k: Kernel) -> tuple[str, str, int | None] | None:
        """-> (twin symbol, how, twin positional arity | None) or None.
        ``how`` is 'annotation', 'name', 'suffix', or 'host'. The arity
        is looked up where the twin actually RESOLVED (oracle scope vs
        same ops file), so the signature check compares the right pair;
        it is None for class twins."""
        if k.twin_decl is not None:
            t = k.twin_decl
            # the FULL dotted target must be registered (classes and
            # their methods both are) — accepting a bare class prefix
            # would let a typoed/deleted method name keep passing
            if t in self.oracle:
                return t, "annotation", self.oracle.get(t)
            if t in self.ops_functions.get(k.path, {}):
                return t, "annotation", self.ops_functions[k.path][t]
            return None
        if k.name in self.oracle:
            return k.name, "name", self.oracle[k.name]
        if k.name.endswith("_device") and k.name[:-7] in self.oracle:
            return k.name[:-7], "suffix", self.oracle[k.name[:-7]]
        here = self.ops_functions.get(k.path, {})
        for suffix in ("_host", "_np"):
            if k.name + suffix in here:
                return k.name + suffix, "host", here[k.name + suffix]
            if k.name + suffix in self.oracle:
                return (k.name + suffix, "suffix",
                        self.oracle[k.name + suffix])
        if k.name.endswith("_device") and k.name[:-7] in here:
            return k.name[:-7], "host", here[k.name[:-7]]
        return None


def is_traced(fn: ast.FunctionDef) -> bool:
    """A body traces when it CALLS into jnp/lax/pl or touches a
    non-dtype attribute of them (``jnp.int8`` alone is a dtype pick)."""
    for node in own_body_nodes(fn):
        if isinstance(node, ast.Attribute):
            d = dotted(node)
            if d is None:
                continue
            root = d.split(".", 1)[0]
            if root in _TRACE_ROOTS and d.split(".")[-1] \
                    not in _DTYPE_ATTRS:
                return True
        elif isinstance(node, ast.Call):
            d = dotted(node.func)
            if d and d.split(".", 1)[0] in _TRACE_ROOTS:
                return True
    return False


def own_body_nodes(fn: ast.FunctionDef):
    """Every node of a function's body exactly once, excluding nested
    function/class defs (those get their own qualnames from
    :func:`functions_in`). THE shared walker — the v3 passes import it
    rather than growing per-module copies that would drift."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def stmt_expr_nodes(st: ast.stmt, skip_lambda: bool = False):
    """Expression nodes of ONE statement (headers only for compounds —
    sub-statements are the statement walkers' business). With
    ``skip_lambda`` a lambda body is opaque: its params are a fresh
    scope (the donation pass's view)."""
    stack = []
    for _name, val in ast.iter_fields(st):
        vals = val if isinstance(val, list) else [val]
        for v in vals:
            if isinstance(v, ast.expr):
                stack.append(v)
    while stack:
        node = stack.pop()
        if skip_lambda and isinstance(node, ast.Lambda):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def functions_in(tree: ast.AST):
    """(qualname, FunctionDef) for every function/method, outer-first."""
    out = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child))
                walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def twin_annotation(sf: SourceFile, fn: ast.FunctionDef) -> str | None:
    """The ``# rtap: twin[...]`` target on the def line, a decorator
    line, or the contiguous comment block directly above them (the
    annotation is usually a 2-line reviewed note)."""
    first = min([fn.lineno] + [d.lineno for d in fn.decorator_list])
    for ln in range(first, fn.lineno + 1):
        if ln - 1 < len(sf.lines):
            m = _TWIN_RE.search(sf.lines[ln - 1])
            if m:
                return m.group(1)
    ln = first - 1
    while ln >= 1 and sf.lines[ln - 1].lstrip().startswith("#"):
        m = _TWIN_RE.search(sf.lines[ln - 1])
        if m:
            return m.group(1)
        ln -= 1
    return None


# ------------------------------------------------- jit decorator parsing --

def _const_strs(node: ast.AST) -> set[str]:
    out = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
    return out


def _const_ints(node: ast.AST) -> set[int]:
    out = set()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
    return out


def jit_decorator_info(fn: ast.FunctionDef) -> dict | None:
    """None when fn carries no jax.jit decorator; else the extracted
    static/donate spec. Handles ``@jax.jit``, ``@jit``, and the
    ``@partial(jax.jit, ...)`` / ``@functools.partial(jax.jit, ...)``
    forms (any partial alias — the repo uses ``_functools`` too)."""
    for dec in fn.decorator_list:
        d = dotted(dec)
        if d in ("jax.jit", "jit"):
            return {"static_argnames": set(), "static_argnums": set(),
                    "donate_argnums": set()}
        if isinstance(dec, ast.Call):
            dfn = dotted(dec.func)
            leaf = dfn.rsplit(".", 1)[-1] if dfn else None
            if dfn in ("jax.jit", "jit"):
                kws = dec.keywords
            elif leaf == "partial" and dec.args \
                    and dotted(dec.args[0]) in ("jax.jit", "jit"):
                kws = dec.keywords
            else:
                continue
            info = {"static_argnames": set(), "static_argnums": set(),
                    "donate_argnums": set()}
            for kw in kws:
                if kw.arg == "static_argnames":
                    info["static_argnames"] = _const_strs(kw.value)
                elif kw.arg == "static_argnums":
                    info["static_argnums"] = _const_ints(kw.value)
                elif kw.arg == "donate_argnums":
                    info["donate_argnums"] = _const_ints(kw.value)
            return info
    return None


def build_kernel_model(ctx: AnalysisContext) -> KernelModel:
    """Build (or return the memoized) kernel model for this context."""
    cached = getattr(ctx, "_kernel_model", None)
    if cached is not None:
        return cached
    model = KernelModel()

    # ---- oracle scope symbols ---------------------------------------
    for sf in ctx.files:
        if sf.tree is None or not any(
                sf.path.startswith(p) for p in ORACLE_SCOPE):
            continue
        for node in sf.tree.body:
            if isinstance(node, ast.FunctionDef):
                model.oracle.setdefault(
                    node.name, len(node.args.args))
            elif isinstance(node, ast.ClassDef):
                model.oracle.setdefault(node.name, None)
                for m in node.body:
                    if isinstance(m, ast.FunctionDef):
                        model.oracle.setdefault(
                            f"{node.name}.{m.name}", None)

    # ---- ops kernels + per-file function tables ---------------------
    for sf in ctx.files_under("rtap_tpu/ops/"):
        if sf.tree is None:
            continue
        table = model.ops_functions.setdefault(sf.path, {})
        for node in sf.tree.body:
            if not isinstance(node, ast.FunctionDef):
                continue
            table[node.name] = len(node.args.args)
            # a kernel either traces itself or is a jit entry point
            # whose body is pure kernel composition (fused_step calls
            # sp_step/tm_step and never names jnp directly)
            if is_traced(node) or jit_decorator_info(node) is not None:
                model.kernels.append(Kernel(
                    name=node.name, path=sf.path, line=node.lineno,
                    node=node, arity=len(node.args.args),
                    public=not node.name.startswith("_"),
                    twin_decl=twin_annotation(sf, node)))

    # ---- jit wrappers across the whole surface ----------------------
    for sf in ctx.files:
        if sf.tree is None:
            continue
        for qual, fn in functions_in(sf.tree):
            info = jit_decorator_info(fn)
            if info is None:
                continue
            w = Wrapper(
                name=fn.name, path=sf.path, line=fn.lineno, node=fn,
                params=[a.arg for a in fn.args.args],
                kwonly=[a.arg for a in fn.args.kwonlyargs],
                static_argnames=info["static_argnames"],
                static_argnums=info["static_argnums"],
                donate_argnums=info["donate_argnums"],
                nested="." in qual)
            model.wrappers.append(w)

    ctx._kernel_model = model
    return model
