"""Single-device idioms in the serve stack: the ROADMAP-1 inventory pass.

Rule ``device-scope`` (ISSUE 15) — the serve stack grew up on one chip
and it shows: ``jax.local_devices()[0]`` reads, blanket ``device_get``
fetches of possibly-sharded state, and flat-stream-id arithmetic that
bypasses the registry's ``SlotAddress{shard, group, slot}`` addressing.
Each one is harmless today and a silent wrong-shard read (or a full
cross-mesh gather on the hot path) the day the fleet spans a v5e-8.
Three findings:

* ``<qual>:device0`` — subscripting ``jax.devices()``/
  ``jax.local_devices()`` (the [0] idiom): on a mesh there is no "the"
  device; iterate or aggregate instead. Declared mesh entry points are
  exempt — they own placement, and picking a device BY SHARD INDEX is
  exactly what the ``# rtap: mesh-entry`` annotation legalizes;
* ``<qual>:fetch:<what>`` — ``jax.device_get(...)`` anywhere, or
  ``np.asarray``/``np.array`` over a state-rooted expression, OUTSIDE a
  declared host boundary (``# rtap: host-boundary — why`` on the def,
  the twin[...] placement grammar; mesh entry points are boundaries by
  construction). Fetching sharded values is legal only where placement
  is owned — everywhere else it is an implicit single-device gather;
* ``<qual>:flat-id:<name>`` — stream/slot arithmetic against group or
  shard extents (``sid // group_size``-shaped), or slot-code bit
  surgery (``SLOT_BITS``/``MAX_*`` masks/shifts) outside the blessed
  addressing modules (service/registry.py, ingest/protocol.py,
  ingest/dispatch.py) — the ONLY places allowed to know how a flat id
  maps onto (shard, group, slot).
"""

from __future__ import annotations

import ast
import re

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.kernels import dotted
from rtap_tpu.analysis.meshmodel import build_mesh_model, scopes_of

PASS_NAME = "device-scope"
PARTITION = "file"
RULES = {
    "device-scope": "single-device idioms in the serve stack: "
                    "devices()[0] reads, device fetches outside "
                    "declared host boundaries, flat-stream-id "
                    "arithmetic bypassing SlotAddress",
}

#: the serve stack (ops/ hot-path fetches are the purity pass's beat)
#: plus the operator tools — scripts' devices()[0] platform probes and
#: fetches are exactly the single-device assumptions the ROADMAP-1
#: inventory must track (each is baselined with a why or fixed)
_SCOPES = ("rtap_tpu/service/", "rtap_tpu/resilience/", "rtap_tpu/obs/",
           "rtap_tpu/correlate/", "rtap_tpu/ingest/",
           "rtap_tpu/__main__.py", "scripts/", "bench.py")

#: the addressing owners: flat-id <-> SlotAddress conversion lives here
#: and nowhere else
_ADDRESSING_OWNERS = ("rtap_tpu/service/registry.py",
                      "rtap_tpu/ingest/protocol.py",
                      "rtap_tpu/ingest/dispatch.py")

#: names whose subscript/attr chains mark an expression "possibly
#: sharded": the group state tree and its common local bindings
_STATE_ROOTS = frozenset({"state", "st", "_states"})

#: slot-code constants only the addressing owners may shift/mask with
_CODE_CONSTS = frozenset({"SLOT_BITS", "GROUP_BITS", "SHARD_BITS",
                          "MAX_SLOTS", "MAX_GROUPS", "MAX_SHARDS"})

_STREAMY_RE = re.compile(
    r"(?:^|_)(?:sid|sids|stream|streams|slot|slots|idx|pos|code|codes)"
    r"(?:$|_)")
_EXTENT_RE = re.compile(
    r"(?:^|\.)(?:group_size|n_groups|num_groups|n_shards|num_shards|"
    r"shards)$")


def _mentions_state(node: ast.AST) -> str | None:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATE_ROOTS:
            return sub.attr
        if isinstance(sub, ast.Name) and sub.id in _STATE_ROOTS:
            return sub.id
    return None


def _side_name(node: ast.AST) -> str | None:
    """The name a BinOp side is 'about': its dotted chain's leaf."""
    d = dotted(node)
    if d is not None:
        return d
    if isinstance(node, ast.Subscript):
        return _side_name(node.value)
    return None


def run(ctx: AnalysisContext) -> list[Finding]:
    model = build_mesh_model(ctx)
    out: list[Finding] = []
    for sf in ctx.files_under(*_SCOPES):
        if sf.tree is None:
            continue
        owner = sf.path in _ADDRESSING_OWNERS
        for qual, nodes in scopes_of(sf):
            boundary = model.is_host_boundary(sf.path, qual)
            # entry points own placement in both directions — a
            # declared mesh entry picking a device BY SHARD INDEX is
            # exactly what the annotation legalizes (docs/ANALYSIS.md)
            entry = model.is_entry(sf.path, qual)
            for node in nodes:
                # ---- devices()[k] ------------------------------------
                if isinstance(node, ast.Subscript) \
                        and isinstance(node.value, ast.Call):
                    d = dotted(node.value.func)
                    if d in ("jax.devices", "jax.local_devices") \
                            and not entry:
                        out.append(Finding(
                            rule="device-scope", path=sf.path,
                            line=node.lineno,
                            symbol=f"{qual}:device0",
                            message=f"indexing {d}() assumes one "
                                    "canonical device — on a mesh "
                                    "there is no [0]; iterate/"
                                    "aggregate over the device list "
                                    "or take the mesh as input"))
                # ---- fetches outside host boundaries -----------------
                elif isinstance(node, ast.Call):
                    d = dotted(node.func)
                    leaf = d.rsplit(".", 1)[-1] if d else None
                    if d == "jax.device_get" and not boundary:
                        out.append(Finding(
                            rule="device-scope", path=sf.path,
                            line=node.lineno,
                            symbol=f"{qual}:fetch:device_get",
                            message="device_get outside a declared "
                                    "host boundary — under a mesh this "
                                    "is a full cross-shard gather; "
                                    "mark the function `# rtap: "
                                    "host-boundary — why` if it owns "
                                    "the materialization, or move the "
                                    "fetch behind one that does"))
                    elif leaf in ("asarray", "array") and d is not None \
                            and d.split(".", 1)[0] in ("np", "numpy") \
                            and node.args and not boundary:
                        root = _mentions_state(node.args[0])
                        if root is not None:
                            out.append(Finding(
                                rule="device-scope", path=sf.path,
                                line=node.lineno,
                                symbol=f"{qual}:fetch:{root}",
                                message=f"np.{leaf} over the state "
                                        "tree outside a declared host "
                                        "boundary — an implicit "
                                        "device->host gather of a "
                                        "possibly-sharded leaf; "
                                        "annotate the boundary or "
                                        "fetch through one"))
                # ---- flat-id arithmetic ------------------------------
                elif isinstance(node, ast.BinOp) and not owner:
                    lname = _side_name(node.left) or ""
                    rname = _side_name(node.right) or ""
                    if isinstance(node.op, (ast.FloorDiv, ast.Mod,
                                            ast.Mult)):
                        pairs = ((lname, rname), (rname, lname))
                        for a, b in pairs:
                            if _STREAMY_RE.search(a.rsplit(".", 1)[-1]) \
                                    and _EXTENT_RE.search(b):
                                out.append(Finding(
                                    rule="device-scope", path=sf.path,
                                    line=node.lineno,
                                    symbol=f"{qual}:flat-id:"
                                           f"{a.rsplit('.', 1)[-1]}",
                                    message="flat-stream-id arithmetic "
                                            "against a group/shard "
                                            "extent — placement math "
                                            "belongs to SlotAddress "
                                            "(service/registry.py, "
                                            "ingest/dispatch.py), not "
                                            "call sites"))
                                break
                    elif isinstance(node.op, (ast.LShift, ast.RShift,
                                              ast.BitAnd, ast.BitOr)):
                        for side in (lname, rname):
                            if side.rsplit(".", 1)[-1] in _CODE_CONSTS:
                                out.append(Finding(
                                    rule="device-scope", path=sf.path,
                                    line=node.lineno,
                                    symbol=f"{qual}:flat-id:"
                                           f"{side.rsplit('.', 1)[-1]}",
                                    message="slot-code bit surgery "
                                            "outside the addressing "
                                            "owners — only ingest/"
                                            "protocol.py may know the "
                                            "shard|group|slot packing"))
                                break
    return out
