"""Partition contracts: every state leaf declares how it lives on the mesh.

Rule ``partition-contract`` (ISSUE 15) — ROADMAP-1's stream-axis
sharding is only safe while every state leaf KNOWS its placement:
``shard-streams`` (the leading G axis splits over the mesh — the SDR
independence property makes this the default for per-stream state),
``replicated`` (every shard holds the full leaf), or ``host-only``
(never device-resident; per-shard process state like the likelihood
moments). An undeclared leaf is exactly the kind of implicit
single-device assumption that turns into silent corruption when a
checkpoint round or journal replay materializes it on the wrong shard.

Rules are DECLARED on the state-tree construction (docs/ANALYSIS.md):

    # rtap: partition[presyn=shard-streams, scores=host-only]   (module)
    "boost": np.ones(C, np.float32),  # rtap: partition[shard-streams]

Constructors are discovered structurally (meshmodel.py): any models/
function building dict literals of numpy/jnp arrays under string keys.
Findings:

* ``<ctor>:unruled:<leaf>`` — a constructed leaf with no declared rule
  (missing coverage);
* ``partition-table:stale:<name>`` — a module-table entry naming no
  constructed leaf (the rule outlived its leaf — coverage must be
  EXACT, both directions);
* ``<qual>:unknown-leaf:<key>`` — a serve-stack consumer subscripting
  a state-like object with a key the declared tree does not contain
  (a renamed leaf whose consumer kept the old string — the drift the
  checkpoint/journal bit-exactness contracts cannot survive);
* ``restore:not-shard-aware`` — some leaf declares ``shard-streams``
  but the checkpoint module never re-places restored state through
  ``shard_state``/``put_sharded`` (a resumed mesh group would silently
  downgrade to single-device);
* ``journal-frame:not-dispatch-routed`` — sharded leaves exist but the
  loop's journal FRAME materialization does not route through
  ``DispatchTable``/``decode_frames_to_row`` (flat-position scatter
  cannot validate shard bits).
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.meshmodel import build_mesh_model, scopes_of

PASS_NAME = "partition-contract"
PARTITION = "program"
RULES = {
    "partition-contract": "state leaves without a declared partition "
                          "rule, stale rule-table entries, consumers "
                          "touching unknown leaves, and un-shard-aware "
                          "checkpoint/journal wiring",
}

#: serve-stack files whose state subscripts are checked against the
#: declared tree
_CONSUMER_SCOPE = ("rtap_tpu/service/", "rtap_tpu/resilience/",
                   "rtap_tpu/obs/", "rtap_tpu/correlate/")

#: receivers treated as "the state tree" at consumer sites: grp.state,
#: a local st/state/model binding, or the oracle's per-stream _states
_STATE_RECEIVERS = frozenset({"state", "st", "model", "_states"})

_CHECKPOINT_FILE = "rtap_tpu/service/checkpoint.py"
_LOOP_FILE = "rtap_tpu/service/loop.py"


def _receiver_name(node: ast.AST) -> str | None:
    """Terminal name of a subscript receiver chain: ``grp.state`` ->
    'state', ``self._states[g]`` -> '_states', ``st`` -> 'st'."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _file_references(sf, names: tuple[str, ...]) -> bool:
    return sf.tree is not None and any(n in sf.text for n in names)


def run(ctx: AnalysisContext) -> list[Finding]:
    model = build_mesh_model(ctx)
    out: list[Finding] = list(model.partition_errors)

    # ---- coverage: every constructed leaf carries a rule -------------
    declared: dict[str, set[str]] = {}   # path -> leaf names built there
    for c in model.constructors:
        table = model.partition_tables.get(c.path, {})
        trailing = model.partition_trailing.get(c.path, {})
        names = declared.setdefault(c.path, set())
        for name, line in c.leaves:
            names.add(name)
            if trailing.get(line) is None and name not in table:
                out.append(Finding(
                    rule="partition-contract", path=c.path, line=line,
                    symbol=f"{c.qual}:unruled:{name}",
                    message=f"state leaf {name!r} has no declared "
                            "partition rule — annotate the construction "
                            "with `# rtap: partition[shard-streams|"
                            "replicated|host-only]` (docs/ANALYSIS.md); "
                            "an undeclared leaf is an implicit "
                            "single-device assumption"))

    # ---- exactness: module-table entries must name real leaves -------
    for path, table in model.partition_tables.items():
        built = declared.get(path, set())
        for name, (_rule, line) in sorted(table.items()):
            if name not in built:
                out.append(Finding(
                    rule="partition-contract", path=path, line=line,
                    symbol=f"partition-table:stale:{name}",
                    message=f"partition rule for {name!r} names no leaf "
                            "any constructor in this file builds — the "
                            "rule outlived its leaf; delete or re-key "
                            "it (coverage must be exact)"))

    if not model.leaf_rules:
        return out   # no state trees in this context (fixture subsets)

    # ---- consumers: string-literal leaf touches must resolve ---------
    for sf in ctx.files_under(*_CONSUMER_SCOPE):
        if sf.tree is None:
            continue
        for qual, nodes in scopes_of(sf):
            for node in nodes:
                if not isinstance(node, ast.Subscript):
                    continue
                if not (isinstance(node.slice, ast.Constant)
                        and isinstance(node.slice.value, str)):
                    continue
                if _receiver_name(node.value) not in _STATE_RECEIVERS:
                    continue
                key = node.slice.value
                if key in model.leaf_rules:
                    continue
                out.append(Finding(
                    rule="partition-contract", path=sf.path,
                    line=node.lineno,
                    symbol=f"{qual}:unknown-leaf:{key}",
                    message=f"consumer touches state leaf {key!r} that "
                            "no models/ constructor declares — a "
                            "renamed/removed leaf whose consumer kept "
                            "the old string would desynchronize "
                            "checkpoint/journal replay"))

    # ---- wiring gates: sharded leaves demand shard-aware plumbing ----
    if any(r == "shard-streams" for r in model.leaf_rules.values()):
        ck = ctx.file(_CHECKPOINT_FILE)
        if ck is not None and not _file_references(
                ck, ("shard_state", "put_sharded")):
            out.append(Finding(
                rule="partition-contract", path=_CHECKPOINT_FILE, line=1,
                symbol="restore:not-shard-aware",
                message="leaves declare shard-streams but the "
                        "checkpoint module never re-places restored "
                        "state via shard_state/put_sharded — a resumed "
                        "mesh group would silently downgrade to "
                        "single-device"))
        lp = ctx.file(_LOOP_FILE)
        if lp is not None and not _file_references(
                lp, ("DispatchTable",)):
            out.append(Finding(
                rule="partition-contract", path=_LOOP_FILE, line=1,
                symbol="journal-frame:not-dispatch-routed",
                message="leaves declare shard-streams but the loop's "
                        "journal FRAME materialization does not route "
                        "through DispatchTable — flat-position scatter "
                        "cannot reject wrong-shard addressing"))
    return out
