"""Flag↔docs drift: every serve flag must be documented.

Rule ``flag-docs`` — the dual of the metric-catalog drift gate
(tests/unit/test_metric_catalog.py, docs/TELEMETRY.md): every
``--flag`` the ``serve`` argparse surface declares in
``rtap_tpu/__main__.py`` must appear somewhere in README.md or
``docs/*.md``. An operator flag nobody documented is a feature nobody
can operate — and three PRs in a row added flags whose docs rode along
only because a reviewer asked.

Detection is AST + line ranges: ``add_parser("serve")`` opens the serve
range (closed by the next ``add_parser``), and every
``add_argument("--x", ...)`` inside it contributes a flag. The docs
check is substring presence of the literal flag text — prose, tables,
and fenced command examples all count.
"""

from __future__ import annotations

import ast
import re

from rtap_tpu.analysis.core import AnalysisContext, Finding

PASS_NAME = "flags"
#: cross-file inputs -> all-or-nothing in the findings cache
PARTITION = "program"
RULES = {
    "flag-docs": "serve argparse flag absent from README.md and "
                 "docs/*.md",
}

MAIN = "rtap_tpu/__main__.py"
SUBCOMMAND = "serve"


def serve_flags(sf) -> list[tuple[str, int]]:
    """(flag, lineno) for every serve-subparser --flag."""
    if sf is None or sf.tree is None:
        return []
    parser_lines: list[tuple[int, str]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "add_parser" \
                and node.args and isinstance(node.args[0], ast.Constant):
            parser_lines.append((node.lineno, str(node.args[0].value)))
    parser_lines.sort()
    lo = hi = None
    for i, (ln, name) in enumerate(parser_lines):
        if name == SUBCOMMAND:
            lo = ln
            hi = parser_lines[i + 1][0] if i + 1 < len(parser_lines) \
                else 10 ** 9
            break
    if lo is None:
        return []
    out = []
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "add_argument" \
                and lo <= node.lineno < hi \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and str(node.args[0].value).startswith("--"):
            out.append((str(node.args[0].value), node.lineno))
    return out


def run(ctx: AnalysisContext) -> list[Finding]:
    sf = ctx.file(MAIN)
    flags = serve_flags(sf)
    if not flags:
        return []
    docs = ctx.docs()
    out = []
    for flag, line in flags:
        # word-boundary match, not substring: `--health` must not ride
        # on a documented `--health-drift-threshold` (the serve surface
        # has ~11 such prefix pairs — exactly the masking this gate
        # exists to catch)
        if not re.search(re.escape(flag) + r"(?![\w-])", docs):
            out.append(Finding(
                rule="flag-docs", path=MAIN, line=line, symbol=flag,
                message=f"serve flag {flag} appears nowhere in README.md "
                        "or docs/*.md — document it (a flag row, a "
                        "runbook mention, or a command example all "
                        "count)"))
    return out
