"""The print gate + strict-coverage pin, ported from check_static.sh.

Rule ``print-strict`` — NO ``print()`` at all in the serve stack
(``service/``, ``obs/``, ``resilience/``, ``ingest/``, ``correlate/``):
telemetry and diagnostics go through rtap_tpu.obs (registry
instruments, watchdog events, snapshots) or logging, never ad-hoc
stdout/stderr lines a harness would have to scrape back out of logs.

Rule ``print-bare`` — everywhere else in ``rtap_tpu/``, ``scripts/``
and ``bench.py``, a ``print()`` must either target an explicit stream
(``file=...`` — stderr diagnostics) or be the sanctioned one-JSON-line
stdout emission (a single ``json.dumps(...)``/``.to_json()`` argument —
the bench/eval artifact contract). AST-based: a line grep cannot see a
multi-line call.

Rule ``strict-coverage`` — the MUST_BE_STRICT pin (ISSUE 11): the
serve-path instrumentation modules must exist AND sit under a strict
directory; a rename/move that silently dropped them out of no-print
coverage would let stdout lines creep back into the hot path. Extend
the list with every new serve-path module.

These rules are gate-critical plumbing, so inline suppressions are NOT
honored for them — the canary tests (tests/unit/test_static_checks.py)
guard the guard.
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding

PASS_NAME = "prints"
#: cross-file inputs -> all-or-nothing in the findings cache
PARTITION = "program"
RULES = {
    "print-strict": "print() in the serve stack (telemetry goes through "
                    "rtap_tpu.obs or logging)",
    "print-bare": "bare print() outside the serve stack (route to "
                  "stderr via file= or emit a JSON artifact line)",
    "strict-coverage": "a pinned serve-path module fell out of strict "
                       "no-print coverage (or vanished)",
}

STRICT_DIRS = ("rtap_tpu/service/", "rtap_tpu/obs/",
               "rtap_tpu/resilience/", "rtap_tpu/ingest/",
               "rtap_tpu/correlate/", "rtap_tpu/fleet/")

#: coverage pin: serve-path instrumentation modules that MUST live under
#: a strict dir. Extend with every new serve-path module.
MUST_BE_STRICT = (
    "rtap_tpu/obs/latency.py",
    "rtap_tpu/obs/slo.py",
    "rtap_tpu/obs/metrics.py",
    "rtap_tpu/service/loop.py",
    "rtap_tpu/fleet/member.py",
    "rtap_tpu/fleet/aggregator.py",
    "rtap_tpu/fleet/control.py",
)


def _allowed_outside_strict(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "file":
            return True  # explicit stream: stderr diagnostics
    if len(call.args) == 1 and isinstance(call.args[0], ast.Call):
        f = call.args[0].func
        if isinstance(f, ast.Attribute) and f.attr in ("dumps", "to_json"):
            return True  # the one-JSON-line stdout artifact contract
    return False


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    paths = {f.path for f in ctx.files}
    for p in MUST_BE_STRICT:
        if p not in paths:
            out.append(Finding(
                rule="strict-coverage", path=p, line=1, symbol=p,
                message="pinned strict module missing — if it moved, "
                        "update MUST_BE_STRICT (rtap_tpu/analysis/"
                        "prints.py) so no-print coverage follows it"))
        elif not any(p.startswith(d) for d in STRICT_DIRS):
            out.append(Finding(
                rule="strict-coverage", path=p, line=1, symbol=p,
                message="pinned module fell out of strict no-print "
                        "coverage"))
    for sf in ctx.files:
        if sf.tree is None:
            continue
        strict = any(sf.path.startswith(d) for d in STRICT_DIRS)
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                continue
            if strict:
                out.append(Finding(
                    rule="print-strict", path=sf.path, line=node.lineno,
                    symbol="print",
                    message="print() in the serve stack — emit through "
                            "rtap_tpu.obs (or logging) instead"))
            elif not _allowed_outside_strict(node):
                out.append(Finding(
                    rule="print-bare", path=sf.path, line=node.lineno,
                    symbol="print",
                    message="bare print() — route to stderr (file=) or "
                            "emit a JSON artifact line"))
    return out
