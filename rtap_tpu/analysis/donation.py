"""Donation discipline: a donated buffer is dead after the dispatch.

Rule ``donate-read`` — ``donate_argnums`` is the memory lever that
makes 100k-stream state updates in-place (ops/step.py: "the TM pools
dominate HBM and the update must happen in place"), and it carries the
nastiest failure mode in the stack: reading the donated binding after
the call returns garbage ON TPU while working perfectly on CPU —
exactly the class tier-1 (CPU-only) can never catch, which is why it
must be a static gate.

The pass takes the jit-wrapper registry from the kernel model
(analysis/kernels.py — every ``@partial(jax.jit, donate_argnums=...)``
in the surface) and, for every function in the program, walks its
statements in source order: a call to a donating wrapper marks the
argument bound to a donated position (a bare name or a dotted
``self.state``-style chain) as DEAD; any later read of that binding
before it is rebound is a finding. The idiomatic call shape —
``state, out = group_step(state, ...)`` — rebinds in the same
statement and never fires.

Scope: every file in the surface (call sites live in service/, bench,
and scripts, not in ops/). Symbol: ``<qual>:<binding>@<wrapper>`` —
line-insensitive. Known limit (documented, deliberate): the walk is
straight-line per function; a loop that donates late in the body and
reads early in the next iteration needs the runtime's donation error
to catch it.
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.kernels import build_kernel_model, dotted, \
    functions_in, stmt_expr_nodes

PASS_NAME = "donation"
PARTITION = "program"
RULES = {
    "donate-read": "read of a jit-donated buffer after the donating "
                   "call (garbage on TPU, works on CPU — invisible to "
                   "tier-1)",
}




def _donated_args(call: ast.Call, wrapper) -> list[str]:
    """Bindings (bare or dotted names) the call donates."""
    out = []
    for i in wrapper.donate_argnums:
        if i < len(call.args):
            d = dotted(call.args[i])
            if d is not None:
                out.append(d)
    donate_names = wrapper.donate_params
    for kw in call.keywords:
        if kw.arg in donate_names:
            d = dotted(kw.value)
            if d is not None:
                out.append(d)
    return out


def _store_targets(st: ast.stmt) -> set[str]:
    """Dotted names this statement (re)binds."""
    out: set[str] = set()
    targets = []
    if isinstance(st, ast.Assign):
        targets = st.targets
    elif isinstance(st, (ast.AnnAssign, ast.AugAssign)):
        targets = [st.target]
    elif isinstance(st, ast.For):
        targets = [st.target]
    elif isinstance(st, ast.With):
        targets = [i.optional_vars for i in st.items
                   if i.optional_vars is not None]
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, (ast.Name, ast.Attribute)):
                d = dotted(n)
                if d is not None:
                    out.add(d)
    return out


def run(ctx: AnalysisContext) -> list[Finding]:
    model = build_kernel_model(ctx)
    donors = [w for w in model.wrappers if w.donate_argnums]
    if not donors:
        return []
    out: list[Finding] = []
    for sf in ctx.files:
        if sf.tree is None:
            continue
        # factory-local wrappers (nested defs) only match call sites in
        # their own file — their bare name proves nothing elsewhere.
        # Same-named donors: the one defined in THIS file wins the name
        # (its call sites are the local one's). The substring prefilter
        # keeps the statement walk off the ~100 files that never name a
        # donor at all (wall-budget discipline).
        file_donors: dict[str, object] = {}
        for w in donors:
            if w.nested and w.path != sf.path:
                continue
            if w.name not in sf.text:
                continue
            if w.name not in file_donors or w.path == sf.path:
                file_donors[w.name] = w
        if not file_donors:
            continue
        for qual, fn in functions_in(sf.tree):
            #: binding -> (wrapper name, donation line)
            dead: dict[str, tuple[str, int]] = {}
            _walk_body(fn.body, dead, out, qual, sf, file_donors)
    return out


def _step_statement(st, dead, out, qual, sf, file_donors) -> None:
    """One statement's OWN expressions (headers only for compounds):
    reads are judged BEFORE this statement's donations are recorded,
    so the idiomatic `state, out = f(state, ...)` never fires — while
    a read (or re-donation) on any later line does."""
    rebound = _store_targets(st)
    for node in stmt_expr_nodes(st, skip_lambda=True):
        if not isinstance(node, (ast.Name, ast.Attribute)):
            continue
        if not isinstance(node.ctx, ast.Load):
            continue
        d = dotted(node)
        if d is None or d not in dead:
            continue
        wname, wline = dead[d]
        out.append(Finding(
            rule="donate-read", path=sf.path,
            line=node.lineno,
            symbol=f"{qual}:{d}@{wname}",
            message=f"`{d}` was donated to {wname}() "
                    f"(line {wline}) and read afterwards "
                    "— donated buffers are garbage on TPU "
                    "after dispatch; rebind the result "
                    "(`x, out = f(x, ...)`) or copy "
                    "before the call"))
        del dead[d]  # one finding per donation site
    for d in rebound:
        dead.pop(d, None)
    for call in _calls_in(st, file_donors):
        w = file_donors[dotted(call.func).rsplit(".", 1)[-1]]
        for d in _donated_args(call, w):
            if d not in rebound:
                dead[d] = (w.name, call.lineno)


def _walk_body(body, dead, out, qual, sf, file_donors) -> None:
    """Source-order statement walk with MUST-analysis over `if`: each
    branch runs with its own copy of the dead set and only bindings
    dead on EVERY branch survive the join — a donation in the if-body
    must not poison the mutually exclusive else (or the code after the
    If, where only one branch ran). Loops/try bodies stay sequential
    (the documented straight-line approximation)."""
    for st in body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        _step_statement(st, dead, out, qual, sf, file_donors)
        if isinstance(st, ast.If):
            d_else = dict(dead)
            _walk_body(st.body, dead, out, qual, sf, file_donors)
            _walk_body(st.orelse, d_else, out, qual, sf, file_donors)
            for k in list(dead):
                if k not in d_else:
                    del dead[k]
            continue
        for attr in ("body", "orelse", "finalbody"):
            _walk_body(getattr(st, attr, []), dead, out, qual, sf,
                       file_donors)
        for h in getattr(st, "handlers", []):
            _walk_body(h.body, dead, out, qual, sf, file_donors)


def _calls_in(st: ast.stmt, donors: dict) -> list[ast.Call]:
    out = []
    for node in stmt_expr_nodes(st, skip_lambda=True):
        if isinstance(node, ast.Call):
            d = dotted(node.func)
            if d is not None and d.rsplit(".", 1)[-1] in donors:
                out.append(node)
    return out
