"""Trace safety: no data-dependent Python control flow in traced code.

Rule ``trace-safety`` — purity (ISSUE 12) bans host *syncs* in kernel
code; this pass (ISSUE 14) extends the scope to host *decisions*. A
``bool()``/``int()``/``float()`` or an ``if`` on a value flowing from a
traced operand is a TracerError under jit at best — and at worst it
traces "successfully" on the first concrete call and silently bakes one
branch into the compiled program. With the Pallas megakernel promotion
(ROADMAP-2) multiplying the traced surface, these must be machine
findings, not review catches. Four shapes, all inside traced
``rtap_tpu/ops/`` functions (traced = calls into jnp/lax/pl):

* ``if``/``while`` whose test reads a *tainted* name —
  symbol ``<qual>:if-on-traced:<var>``;
* ``bool()``/``int()``/``float()`` (or ``.tolist()``) over a tainted
  value — symbol ``<qual>:py-cast:<fn>``;
* ``np.*`` calls fed a tainted value (a host round-trip beyond the
  purity-fetch set) — symbol ``<qual>:host-call:<fn>``;
* data-dependent output shapes: one-arg ``jnp.where`` and
  ``jnp.nonzero``/``flatnonzero``/``argwhere``/``unique`` without
  ``size=`` — symbol ``<qual>:shape-trap:<fn>`` (these trap regardless
  of taint: the shape depends on VALUES).

Taint is deliberately conservative (near-zero false positives): sources
are parameters annotated ``jnp.ndarray``/``jax.Array`` and locals
assigned from jnp/lax expressions; it propagates through assignments in
source order but NOT through ``.shape``/``.ndim``/``.dtype``/``.size``
(shapes are static under jit — ``if x.shape[0] > 8:`` is legal trace
specialization, ``if x > 8:`` is the bug).
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.kernels import dotted, functions_in, is_traced, \
    own_body_nodes

PASS_NAME = "trace-safety"
PARTITION = "file"
RULES = {
    "trace-safety": "data-dependent Python control flow, py-cast, "
                    "host call, or value-dependent output shape inside "
                    "traced ops/ code",
}

#: attribute hops that launder taint away: static under jit
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "itemsize"})

#: value-dependent-shape calls; where is special-cased (1-arg form only)
_SHAPE_TRAPS = ("nonzero", "flatnonzero", "argwhere", "unique")

_ARRAY_ANNOTATIONS = ("jnp.ndarray", "jax.Array", "jnp.array",
                      "jax.numpy.ndarray")


def _annotation_is_array(ann: ast.AST | None) -> bool:
    if ann is None:
        return False
    for node in ast.walk(ann):
        d = dotted(node) if isinstance(node, ast.Attribute) else None
        if d in _ARRAY_ANNOTATIONS:
            return True
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value in _ARRAY_ANNOTATIONS:
            return True
    return False


def _tainted_names(expr: ast.AST, tainted: set[str],
                   skip_identity: bool = False) -> set[str]:
    """Tainted names read by expr, NOT reached through a static
    (.shape-style) attribute hop. ``skip_identity`` additionally skips
    ``is None``-style comparisons (for `if` tests: identity clauses are
    structural, `x.shape[0] > 2 and prev is not None` is legal)."""
    hits: set[str] = set()

    def rec(node):
        if isinstance(node, ast.Attribute) and node.attr in _STATIC_ATTRS:
            return  # static under jit: taint stops here
        if skip_identity and isinstance(node, ast.Compare) \
                and all(isinstance(op, (ast.Is, ast.IsNot))
                        for op in node.ops):
            return
        if isinstance(node, ast.Name) and node.id in tainted:
            hits.add(node.id)
        for child in ast.iter_child_nodes(node):
            rec(child)

    rec(expr)
    return hits


def _expr_traces(expr: ast.AST) -> bool:
    """Expr builds on jnp/lax (so its value is traced)."""
    for node in ast.walk(expr):
        d = None
        if isinstance(node, (ast.Attribute, ast.Name)):
            d = dotted(node)
        if d and d.split(".", 1)[0] in ("jnp", "lax"):
            return True
    return False


def _taint_fixpoint(fn: ast.FunctionDef) -> set[str]:
    """Names carrying traced values: array-annotated params plus every
    assignment target fed (transitively) by jnp/lax or a tainted name.
    Iterated to a fixed point so assignment ORDER inside loops cannot
    hide a flow (over-taints reads-before-binding — fine for a gate
    that wants zero false negatives on control flow)."""
    tainted: set[str] = {
        a.arg for a in fn.args.args + fn.args.kwonlyargs
        if _annotation_is_array(a.annotation)}
    assigns = [
        (st.targets if isinstance(st, ast.Assign) else [st.target],
         st.value)
        for st in own_body_nodes(fn)
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign))
        and st.value is not None]
    changed = True
    while changed:
        changed = False
        for targets, value in assigns:
            if _expr_traces(value) or _tainted_names(value, tainted):
                for t in targets:
                    for n in _name_targets(t):
                        if n not in tainted:
                            tainted.add(n)
                            changed = True
    return tainted


def _name_targets(t: ast.AST):
    """BARE names a target binds — attribute/subscript targets are
    skipped (``self.state`` stores to an object, it does not create a
    local the taint set tracks; walking into it would falsely taint
    ``self``)."""
    if isinstance(t, ast.Name):
        yield t.id
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            yield from _name_targets(e)
    elif isinstance(t, ast.Starred):
        yield from _name_targets(t.value)




def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    for sf in ctx.files_under("rtap_tpu/ops/"):
        if sf.tree is None:
            continue
        for qual, fn in functions_in(sf.tree):
            # top-level functions only: this codebase's kernels are
            # pure module-level functions; methods are host-boundary
            # wrappers (TpuStepRunner.step) whose float()/if ARE the
            # boundary, and nested closures trace inside their parent
            if "." in qual or not is_traced(fn):
                continue
            tainted = _taint_fixpoint(fn)
            for node in own_body_nodes(fn):
                # ---- if/while on traced values ----------------------
                if isinstance(node, (ast.If, ast.While)):
                    for var in sorted(_tainted_names(
                            node.test, tainted, skip_identity=True)):
                        out.append(Finding(
                            rule="trace-safety", path=sf.path,
                            line=node.lineno,
                            symbol=f"{qual}:if-on-traced:{var}",
                            message=f"Python `if` on traced value "
                                    f"`{var}` — under jit this is a "
                                    "concretization error (or silently "
                                    "bakes one branch in); use "
                                    "jnp.where / lax.cond"))
                    continue
                if isinstance(node, ast.Call):
                    d = dotted(node.func)
                    if isinstance(node.func, ast.Name) \
                            and node.func.id in ("bool", "int", "float") \
                            and any(_tainted_names(a, tainted)
                                    for a in node.args):
                        out.append(Finding(
                            rule="trace-safety", path=sf.path,
                            line=node.lineno,
                            symbol=f"{qual}:py-cast:{node.func.id}",
                            message=f"{node.func.id}() over a traced "
                                    "value — a host concretization "
                                    "under jit; keep the value on "
                                    "device (astype) or move the cast "
                                    "to the host boundary"))
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "tolist" \
                            and _tainted_names(node.func.value, tainted):
                        out.append(Finding(
                            rule="trace-safety", path=sf.path,
                            line=node.lineno,
                            symbol=f"{qual}:py-cast:tolist",
                            message=".tolist() over a traced value — "
                                    "a host fetch under jit"))
                    elif d and (d.startswith("np.")
                                or d.startswith("numpy.")) \
                            and any(_tainted_names(a, tainted)
                                    for a in node.args):
                        out.append(Finding(
                            rule="trace-safety", path=sf.path,
                            line=node.lineno,
                            symbol=f"{qual}:host-call:{d}",
                            message=f"{d}() fed a traced value — a "
                                    "host round-trip beyond the "
                                    "purity-fetch set; use the jnp "
                                    "equivalent"))
                    # ---- value-dependent output shapes --------------
                    if d == "jnp.where" and len(node.args) == 1:
                        out.append(Finding(
                            rule="trace-safety", path=sf.path,
                            line=node.lineno,
                            symbol=f"{qual}:shape-trap:where",
                            message="one-arg jnp.where returns a "
                                    "value-dependent shape — untraceable"
                                    "; use the three-arg form or "
                                    "jnp.nonzero(..., size=)"))
                    elif d and d.startswith("jnp.") \
                            and d.split(".")[-1] in _SHAPE_TRAPS \
                            and not any(kw.arg == "size"
                                        for kw in node.keywords):
                        out.append(Finding(
                            rule="trace-safety", path=sf.path,
                            line=node.lineno,
                            symbol=f"{qual}:shape-trap:"
                                   f"{d.split('.')[-1]}",
                            message=f"{d}() without size= returns a "
                                    "value-dependent shape — pass "
                                    "size= (with fill_value) to keep "
                                    "the program traceable"))
    return out
