"""Static deadlock detection: the global lock-acquisition graph.

Rule ``lock-order`` — build the program-wide graph whose nodes are lock
attributes (``Class.attr``, one node per class lock — instances are
conflated, the conservative direction) and whose edges L1 -> L2 mean
"somewhere, L2 is acquired while L1 is held". Any cycle is a potential
deadlock: two threads entering the cycle from different edges can each
hold one lock and wait forever on the other. The ``Lease``
``_lock``/``_seen_lock`` nesting (PR 8) was exactly this class of bug,
caught by hand in review; this pass is that reviewer, made permanent.

Edges come from three site shapes, all interprocedural:

* lexical nesting — ``with self.a: ... with self.b:`` adds a -> b;
* in-class calls — ``with self.a: self._m()`` adds a -> every lock in
  ``_m``'s *acquisition closure* (every lock the call graph under
  ``_m`` can take, computed as a worklist fixed point — the races-pass
  worklist idea, pointed at acquisitions instead of guards);
* cross-class calls — ``with self.a: self.worker.push()`` adds a ->
  every lock in ``Worker.push``'s closure, where ``self.worker``'s
  candidate classes come from the whole-program model's
  constructor-injection typing (rtap_tpu/analysis/program.py). Every
  candidate contributes edges: a may-analysis that guessed one class
  would silently drop real deadlock edges.

A *self*-edge — re-acquiring a lock already held on some path — is
reported only when the lock is known non-reentrant
(``threading.Lock``): with an ``RLock``/``Condition`` the nesting is
legal. That is the ``Lease.read``-inside-``refresh`` near-miss: had
``read()`` taken ``self._lock`` (which ``refresh`` already holds), this
pass would have flagged the exact line.

Findings carry the cycle as their symbol (``A._x->B._y->A._x``,
canonicalized to start at the smallest node so the symbol is stable no
matter which edge the walker found first) and anchor on one
acquisition site inside the cycle, so a suppression lands where a human
would look first.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.program import (
    ClassInfo,
    build_program,
    dotted,
)

PASS_NAME = "lock-order"
#: cross-file inputs -> all-or-nothing in the findings cache
PARTITION = "program"
RULES = {
    "lock-order": "cycle in the global lock-acquisition graph (or a "
                  "non-reentrant lock re-acquired on a path that "
                  "already holds it) — a static deadlock",
}

#: whole serve stack + the CLI wiring that constructs it
SCOPE = ("rtap_tpu/service/", "rtap_tpu/obs/", "rtap_tpu/resilience/",
         "rtap_tpu/ingest/", "rtap_tpu/correlate/", "rtap_tpu/fleet/",
         "rtap_tpu/__main__.py")


@dataclass(frozen=True)
class _Edge:
    src: str            # lock id "Class.attr"
    dst: str
    path: str           # file of the acquisition/call site
    line: int
    why: str            # human fragment for the message


class _MethodScan(ast.NodeVisitor):
    """One method body: lock acquisitions, self-calls and collaborator
    calls, each annotated with the lexically-held lock set."""

    def __init__(self, ci: ClassInfo, self_names: set[str]):
        self.ci = ci
        self.self_names = self_names
        self._held: list[str] = []          # lock ATTR names, lexical
        #: (lock_attr, line, held-before frozenset of attrs)
        self.acquisitions: list[tuple[str, int, frozenset]] = []
        #: (callee method name, line, held frozenset)
        self.self_calls: list[tuple[str, int, frozenset]] = []
        #: (collab attr, callee method name, line, held frozenset)
        self.collab_calls: list[tuple[str, str, int, frozenset]] = []

    # nested defs run later, on other stacks — not this method's order
    def visit_FunctionDef(self, node):  # noqa: N802
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):  # noqa: N802
        pass

    def visit_Lambda(self, node):  # noqa: N802
        pass

    def _lock_attr_of(self, expr: ast.AST) -> str | None:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id in self.self_names \
                and expr.attr in self.ci.lock_attrs:
            return expr.attr
        return None

    def visit_With(self, node):  # noqa: N802
        taken = []
        for it in node.items:
            attr = self._lock_attr_of(it.context_expr)
            if attr is not None:
                self.acquisitions.append(
                    (attr, it.context_expr.lineno, frozenset(self._held)))
                self._held.append(attr)
                taken.append(attr)
        for st in node.body:
            self.visit(st)
        if taken:
            del self._held[-len(taken):]

    def visit_Call(self, node):  # noqa: N802
        f = node.func
        if isinstance(f, ast.Attribute):
            # self.<lock>.acquire()/.release() — the explicit form.
            # acquire EXTENDS the held set for the rest of the scan
            # (release pops it): lexically approximate, but without it
            # every ordering edge OUT of an explicitly-acquired lock is
            # invisible and explicit-acquire code bypasses the gate
            attr = self._lock_attr_of(f.value)
            if attr is not None and f.attr == "acquire":
                self.acquisitions.append(
                    (attr, node.lineno, frozenset(self._held)))
                self._held.append(attr)
            elif attr is not None and f.attr == "release":
                for i in range(len(self._held) - 1, -1, -1):
                    if self._held[i] == attr:
                        del self._held[i]
                        break
            elif isinstance(f.value, ast.Name) \
                    and f.value.id in self.self_names \
                    and f.attr in self.ci.methods:
                self.self_calls.append(
                    (f.attr, node.lineno, frozenset(self._held)))
            elif isinstance(f.value, ast.Attribute) \
                    and isinstance(f.value.value, ast.Name) \
                    and f.value.value.id in self.self_names \
                    and f.value.attr in self.ci.collab_attrs:
                self.collab_calls.append(
                    (f.value.attr, f.attr, node.lineno,
                     frozenset(self._held)))
        self.generic_visit(node)


def _scan_method(ci: ClassInfo, m: ast.FunctionDef) -> _MethodScan:
    self_names = {m.args.args[0].arg} if m.args.args else set()
    sc = _MethodScan(ci, self_names)
    for st in m.body:
        sc.visit(st)
    return sc


def _closures(scans: dict[tuple[str, str], _MethodScan],
              prog) -> dict[tuple[str, str], frozenset]:
    """Acquisition closure per (class, method): every lock id the call
    graph under that method may take. Union fixed point (monotone
    increasing over a finite lattice, so it terminates)."""
    clo: dict[tuple[str, str], set] = {}
    for key, sc in scans.items():
        cname = key[0]
        clo[key] = {f"{cname}.{a}" for a, _l, _h in sc.acquisitions}
    changed = True
    while changed:
        changed = False
        for (cname, mname), sc in scans.items():
            cur = clo[(cname, mname)]
            before = len(cur)
            for callee, _l, _h in sc.self_calls:
                cur |= clo.get((cname, callee), set())
            for cattr, callee, _l, _h in sc.collab_calls:
                ci = prog.classes.get(cname)
                for tname in sorted(ci.collab_attrs.get(cattr, ())):
                    cur |= clo.get((tname, callee), set())
            if len(cur) != before:
                changed = True
    return {k: frozenset(v) for k, v in clo.items()}


def _canonical_cycle(nodes: list[str]) -> str:
    """Rotate the cycle to start at its smallest node: a stable symbol
    regardless of traversal order."""
    i = nodes.index(min(nodes))
    rot = nodes[i:] + nodes[:i]
    return "->".join(rot + [rot[0]])


def _find_cycles(edges: list[_Edge]) -> list[list[str]]:
    """Elementary cycles via DFS over the (small) lock graph. One cycle
    reported per distinct node set — enough to name the knot without
    enumerating every rotation."""
    graph: dict[str, set[str]] = {}
    for e in edges:
        if e.src != e.dst:
            graph.setdefault(e.src, set()).add(e.dst)
            graph.setdefault(e.dst, set())
    cycles: list[list[str]] = []
    seen_sets: set[frozenset] = set()

    def dfs(start: str, node: str, path: list[str], on_path: set[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(list(path))
            elif nxt not in on_path and nxt > start:
                # only walk nodes > start: each cycle is found exactly
                # once, rooted at its smallest node
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def run(ctx: AnalysisContext) -> list[Finding]:
    prog = build_program(ctx)
    scope_paths = set()
    for sf in ctx.files_under(*SCOPE):
        scope_paths.add(sf.path)

    scans: dict[tuple[str, str], _MethodScan] = {}
    lines: dict[tuple[str, str], int] = {}  # method def line, for anchors
    for ci in prog.classes.values():
        if ci.path not in scope_paths or not ci.lock_attrs \
                and not ci.collab_attrs:
            continue
        for mname, m in ci.methods.items():
            scans[(ci.name, mname)] = _scan_method(ci, m)
            lines[(ci.name, mname)] = m.lineno

    clo = _closures(scans, prog)

    edges: list[_Edge] = []
    out: list[Finding] = []
    reported_self: set[tuple[str, str]] = set()  # (lock id, site key)
    for (cname, mname), sc in sorted(scans.items()):
        ci = prog.classes[cname]
        # lexical/explicit acquisitions while other locks held
        for attr, line, held in sc.acquisitions:
            dst = ci.lock_id(attr)
            for h in sorted(held):
                src = ci.lock_id(h)
                if src == dst:
                    if not ci.lock_attrs.get(attr, True) \
                            and (dst, f"{ci.path}:{line}") \
                            not in reported_self:
                        reported_self.add((dst, f"{ci.path}:{line}"))
                        out.append(Finding(
                            rule="lock-order", path=ci.path, line=line,
                            symbol=f"{dst}->{dst}",
                            message=f"{dst} is a non-reentrant "
                                    "threading.Lock re-acquired on a "
                                    "path that already holds it — a "
                                    "guaranteed self-deadlock; use an "
                                    "RLock or split the inner state "
                                    "onto its own lock (the "
                                    "Lease._seen_lock fix)"))
                else:
                    edges.append(_Edge(
                        src, dst, ci.path, line,
                        f"{cname}.{mname} acquires {dst} while "
                        f"holding {src}"))
        # calls made while holding locks: edges into the callee closure
        call_sites = [
            ((cname, callee), line, held)
            for callee, line, held in sc.self_calls] + [
            ((tname, callee), line, held)
            for cattr, callee, line, held in sc.collab_calls
            for tname in sorted(ci.collab_attrs.get(cattr, ()))]
        for key, line, held in call_sites:
            if not held or key not in clo:
                continue
            for h in sorted(held):
                src = ci.lock_id(h)
                for dst in sorted(clo[key]):
                    if dst == src:
                        # reentrancy is a property of the lock's OWNING
                        # class (dst's prefix), not of the callee: the
                        # re-acquisition may be reached through a
                        # collaborator round-trip (A -> B -> A)
                        dcls, dattr = dst.split(".", 1)
                        owner = prog.classes.get(dcls)
                        reent = owner.lock_attrs.get(dattr, True) \
                            if owner is not None else True
                        if not reent and (dst, f"{ci.path}:{line}") \
                                not in reported_self:
                            reported_self.add((dst, f"{ci.path}:{line}"))
                            out.append(Finding(
                                rule="lock-order", path=ci.path,
                                line=line, symbol=f"{dst}->{dst}",
                                message=f"call from {cname}.{mname} "
                                        f"(holding {src}) reaches a "
                                        f"re-acquisition of the same "
                                        "non-reentrant lock in "
                                        f"{key[0]}.{key[1]} — a "
                                        "self-deadlock on this path"))
                    else:
                        edges.append(_Edge(
                            src, dst, ci.path, line,
                            f"{cname}.{mname} calls {key[0]}.{key[1]} "
                            f"(which may take {dst}) while holding "
                            f"{src}"))

    for cyc in _find_cycles(edges):
        symbol = _canonical_cycle(cyc)
        nodes = set(cyc)
        # anchor on the smallest (path, line) edge inside the cycle so
        # the finding (and any suppression) lands deterministically
        in_cycle = [e for e in edges if e.src in nodes and e.dst in nodes]
        anchor = min(in_cycle, key=lambda e: (e.path, e.line))
        detail = "; ".join(sorted({e.why for e in in_cycle})[:4])
        out.append(Finding(
            rule="lock-order", path=anchor.path, line=anchor.line,
            symbol=symbol,
            message=f"lock-order cycle {symbol}: {detail} — two threads "
                    "entering from different edges deadlock; impose one "
                    "global order (acquire in symbol order) or collapse "
                    "to a single lock"))
    return out
