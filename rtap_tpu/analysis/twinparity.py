"""Twin parity: every public device kernel has an oracle twin AND a
parity test.

Rule ``twin-parity`` — the static gate under the repo's core claim
("every kernel has a numpy oracle twin and bit-exact parity tests",
ops/__init__.py). Three ways a kernel fails it:

* **untwinned** — no oracle twin resolves (by name pairing against
  ``rtap_tpu/models/`` + ``rtap_tpu/utils/hashing.py``, by the
  ``_np``/``_host``/``_device`` suffix conventions, or by an explicit
  ``# rtap: twin[Target]`` annotation — see analysis/kernels.py);
* **signature** — a *name-paired* function twin disagrees on positional
  arity (an annotated pairing is the reviewed assertion and only has to
  resolve — state-dict vs explicit-tensor calling conventions are why
  annotations exist);
* **untested** — the kernel's name appears in no ``tests/parity/`` file.
  This is what makes deleting a parity test a GATE failure instead of a
  silent coverage hole: the parity tree is an analyzer input (it rides
  the findings-cache key exactly like the docs text).

Scope: public top-level traced functions in ``rtap_tpu/ops/`` (traced =
calls into jnp/lax/pl — a dtype helper that only names ``jnp.int16``
is not a kernel). Symbols are ``<kernel>:untwinned`` /
``<kernel>:signature`` / ``<kernel>:untested`` — line-insensitive, so
baselining survives edits.
"""

from __future__ import annotations

import re

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.kernels import build_kernel_model

PASS_NAME = "twin-parity"
PARTITION = "program"
RULES = {
    "twin-parity": "public ops/ kernel with no resolvable oracle twin, "
                   "an arity-incompatible name-paired twin, or no "
                   "tests/parity/ coverage",
}


def run(ctx: AnalysisContext) -> list[Finding]:
    model = build_kernel_model(ctx)
    if not model.kernels:
        return []
    parity = ctx.parity()
    out: list[Finding] = []
    for k in model.kernels:
        if not k.public:
            continue
        resolved = model.resolve_twin(k)
        if resolved is None:
            how = "annotation target does not resolve" \
                if k.twin_decl is not None else "no twin resolves"
            out.append(Finding(
                rule="twin-parity", path=k.path, line=k.line,
                symbol=f"{k.name}:untwinned",
                message=f"{how} for public kernel {k.name} — pair it "
                        "with its oracle (same name, _np/_host suffix) "
                        "or declare `# rtap: twin[Target]` on the def "
                        "(docs/ANALYSIS.md); an untwinned kernel has "
                        "no bit-exactness story"))
        else:
            twin, via, arity = resolved
            if via in ("name", "suffix", "host"):
                if arity is not None and arity != k.arity:
                    out.append(Finding(
                        rule="twin-parity", path=k.path, line=k.line,
                        symbol=f"{k.name}:signature",
                        message=f"kernel {k.name} takes {k.arity} "
                                f"positional args but its name-paired "
                                f"twin {twin} takes {arity} — align "
                                "the signatures or declare the "
                                "reviewed pairing with "
                                f"`# rtap: twin[{twin}]`"))
        if not re.search(rf"\b{re.escape(k.name)}\b", parity):
            out.append(Finding(
                rule="twin-parity", path=k.path, line=k.line,
                symbol=f"{k.name}:untested",
                message=f"public kernel {k.name} appears in no "
                        "tests/parity/ file — bit-exactness is only a "
                        "claim until a parity test exercises it "
                        "(removing that test re-fails this gate)"))
    return out
