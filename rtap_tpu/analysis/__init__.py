"""rtap-lint: AST-based invariant analysis for the serve stack (ISSUE 12).

The repo's correctness story rests on contracts no test fully covers —
bit-exact device/oracle twins, exactly-once alert delivery, and a lock
discipline across ~10 daemon-threaded modules. Three review passes
found the same latent-bug classes by hand; this package machine-checks
them:

==================  ====================================================
pass (module)       rules
==================  ====================================================
races               ``race`` (thread-shared-state write/write races with
                    interprocedural lock inference), ``thread-name``
                    (anonymous serve-stack threads)
purity              ``purity-nondet``, ``purity-fetch``,
                    ``purity-isfinite`` (hot-path determinism, no
                    device fetches, not-NaN presence contract)
excepts             ``except-silent`` (bare-pass handlers in the serve
                    stack)
flags               ``flag-docs`` (serve flags absent from README/docs —
                    the metric-catalog gate's dual)
prints              ``print-strict``, ``print-bare``,
                    ``strict-coverage`` (the check_static.sh gate,
                    ported; non-suppressible)
==================  ====================================================

CLI: ``python -m rtap_tpu.analysis`` (human report, exit 0 iff zero
unsuppressed findings; ``--json`` emits one artifact line for soaks).
``scripts/check_static.sh`` is a thin wrapper (compileall + one analyzer
invocation) and rides tier-1 via tests/unit/test_static_checks.py.
Suppression/baseline syntax and the triage runbook: docs/ANALYSIS.md.
"""

from __future__ import annotations

from rtap_tpu.analysis import excepts, flags, prints, purity, races
from rtap_tpu.analysis.core import (  # noqa: F401
    AnalysisContext,
    Baseline,
    Finding,
    Report,
    SourceFile,
    run_analysis,
)

#: execution order: cheap syntactic passes first, the interprocedural
#: race pass last (ordering is cosmetic — every pass always runs)
PASSES = (prints, excepts, flags, purity, races)

#: rule id -> description, across every pass (the CLI's --list-passes)
ALL_RULES = {rid: desc for mod in PASSES for rid, desc in mod.RULES.items()}
