"""rtap-lint: AST-based invariant analysis for the serve stack
(ISSUEs 12 + 13).

The repo's correctness story rests on contracts no test fully covers —
bit-exact device/oracle twins, exactly-once alert delivery, and a lock
discipline across ~10 daemon-threaded modules. Three review passes
found the same latent-bug classes by hand; this package machine-checks
them. v1 (ISSUE 12) was per-class/intra-module; v2 (ISSUE 13) adds
whole-program passes over the shared model in
``rtap_tpu/analysis/program.py``:

==================  ====================================================
pass (module)       rules
==================  ====================================================
races               ``race`` (thread-shared-state write/write races with
                    interprocedural lock inference), ``thread-name``
                    (anonymous serve-stack threads)
purity              ``purity-nondet``, ``purity-fetch``,
                    ``purity-isfinite`` (hot-path determinism, no
                    device fetches, not-NaN presence contract)
excepts             ``except-silent`` (bare-pass handlers in the serve
                    stack)
flags               ``flag-docs`` (serve flags absent from README/docs —
                    the metric-catalog gate's dual)
prints              ``print-strict``, ``print-bare``,
                    ``strict-coverage`` (the check_static.sh gate,
                    ported; non-suppressible)
lockorder           ``lock-order`` (cycles in the global
                    lock-acquisition graph — static deadlock detection,
                    interprocedural across classes and modules)
crossshare          ``cross-share`` (objects handed to both a
                    thread-running class and another consumer, mutated
                    in place on one side and read on the other —
                    the retired docs/ANALYSIS.md hand-audit list)
determinism         ``replay-determinism`` (unsorted set/listdir
                    iteration or float reductions feeding
                    serialization/hashing paths)
lifecycle           ``resource-lifecycle`` (class-owned threads/sockets/
                    shm/files with no reachable bounded-join/close on
                    the teardown path)
==================  ====================================================

CLI: ``python -m rtap_tpu.analysis`` (human report, exit 0 iff zero
unsuppressed findings; ``--json`` emits one artifact line for soaks,
``--sarif PATH`` writes a SARIF 2.1.0 log for CI/editor rendering).
Incremental runs are served from a per-file content-hash findings cache
(``--no-cache`` forces a cold run; cached and cold runs are
finding-identical by test). ``scripts/check_static.sh`` is a thin
wrapper (compileall + one analyzer invocation) and rides tier-1 via
tests/unit/test_static_checks.py. Suppression/baseline syntax and the
triage runbook: docs/ANALYSIS.md.
"""

from __future__ import annotations

from rtap_tpu.analysis import (
    crossshare,
    determinism,
    excepts,
    flags,
    lifecycle,
    lockorder,
    prints,
    purity,
    races,
)
from rtap_tpu.analysis.core import (  # noqa: F401
    AnalysisContext,
    Baseline,
    Finding,
    Report,
    SourceFile,
    run_analysis,
)

#: execution order: cheap syntactic passes first, then the
#: interprocedural per-class pass, then the whole-program v2 passes
#: (ordering is cosmetic — every pass always runs)
PASSES = (prints, excepts, flags, purity, races,
          determinism, lifecycle, lockorder, crossshare)

#: rule id -> description, across every pass (the CLI's --list-passes)
ALL_RULES = {rid: desc for mod in PASSES for rid, desc in mod.RULES.items()}
