"""rtap-lint: AST-based invariant analysis for the serve stack AND the
device-kernel surface (ISSUEs 12 + 13 + 14 + 15).

The repo's correctness story rests on contracts no test fully covers —
bit-exact device/oracle twins, exactly-once alert delivery, and a lock
discipline across ~10 daemon-threaded modules. Three review passes
found the same latent-bug classes by hand; this package machine-checks
them. v1 (ISSUE 12) was per-class/intra-module; v2 (ISSUE 13) added
whole-program passes over the shared model in
``rtap_tpu/analysis/program.py``; v3 (ISSUE 14) crosses the
host/device boundary with a kernel model
(``rtap_tpu/analysis/kernels.py``: jit-wrapper discovery with
static/donate extraction, the ops/ ↔ oracle/ twin registry) feeding
six device passes; v4 (ISSUE 15) adds the mesh-readiness family over a
mesh model (``rtap_tpu/analysis/meshmodel.py``: mesh entry points,
host boundaries, partition-rule tables, the shard-resource registry) —
the machine-checked work inventory for ROADMAP-1's pod-scale sharding:

==================  ====================================================
pass (module)       rules
==================  ====================================================
races               ``race`` (thread-shared-state write/write races with
                    interprocedural lock inference), ``thread-name``
                    (anonymous serve-stack threads)
purity              ``purity-nondet``, ``purity-fetch``,
                    ``purity-isfinite`` (hot-path determinism, no
                    device fetches, not-NaN presence contract)
excepts             ``except-silent`` (bare-pass handlers in the serve
                    stack)
flags               ``flag-docs`` (serve flags absent from README/docs —
                    the metric-catalog gate's dual)
prints              ``print-strict``, ``print-bare``,
                    ``strict-coverage`` (the check_static.sh gate,
                    ported; non-suppressible)
lockorder           ``lock-order`` (cycles in the global
                    lock-acquisition graph — static deadlock detection,
                    interprocedural across classes and modules)
crossshare          ``cross-share`` (objects handed to both a
                    thread-running class and another consumer, mutated
                    in place on one side and read on the other —
                    the retired docs/ANALYSIS.md hand-audit list)
determinism         ``replay-determinism`` (unsorted set/listdir
                    iteration or float reductions feeding
                    serialization/hashing paths)
lifecycle           ``resource-lifecycle`` (class-owned threads/sockets/
                    shm/files with no reachable bounded-join/close on
                    the teardown path)
twinparity          ``twin-parity`` (every public ops/ kernel resolves
                    to an oracle twin with a compatible signature AND
                    appears in a tests/parity/ file)
tracesafety         ``trace-safety`` (no data-dependent Python control
                    flow, py-casts, host calls, or value-dependent
                    output shapes inside traced kernels)
donation            ``donate-read`` (no read of a jit-donated buffer
                    after the donating dispatch)
statichash          ``static-hash``, ``jit-churn`` (hashable/frozen
                    static args naming live params; no jax.jit built
                    inside loops or over lambdas)
dtypedomain         ``dtype-domain`` (declared u8|u16|i32-key domains:
                    no silent cross-grid mixes, unclamped i32-key
                    multiplies, or undeclared quantized casts)
wirecontract        ``wire-contract`` (RB1/RJ struct formats, magics,
                    and type codes cross-checked against the wire docs)
partition           ``partition-contract`` (every state leaf declares
                    shard-streams|replicated|host-only; coverage exact;
                    consumers and checkpoint/journal wiring agree)
devicescope         ``device-scope`` (devices()[0] reads, device
                    fetches outside declared host boundaries, flat-
                    stream-id arithmetic bypassing SlotAddress)
collectives         ``collective-discipline`` (psum/all_gather/
                    ppermute/shard_map banned outside declared mesh
                    entry points — sharded_chunk_step stays
                    collective-free by gate)
shardresource       ``shard-resource`` (journal/checkpoint/lease/
                    sidecar paths derive from service/shardpath.py,
                    never bare concat)
scalingmath         ``scaling-math`` (SCALING.md bytes/stream +
                    streams/chip cross-checked against a static
                    derivation from the config dataclasses)
==================  ====================================================

CLI: ``python -m rtap_tpu.analysis`` (human report, exit 0 iff zero
unsuppressed findings; ``--json`` emits one artifact line for soaks,
``--sarif PATH`` writes a SARIF 2.1.0 log for CI/editor rendering,
``--update-baseline`` does mechanical baseline maintenance without
ever minting a why-less entry). Incremental runs are served from the
pass-partitioned content-hash findings cache (``--no-cache`` forces a
cold run; cold/warm/hit runs are finding-identical by test).
``scripts/check_static.sh`` is a thin wrapper (compileall + one
analyzer invocation) and rides tier-1 via
tests/unit/test_static_checks.py. Suppression/annotation/baseline
syntax and the triage runbook: docs/ANALYSIS.md.
"""

from __future__ import annotations

from rtap_tpu.analysis import (
    collectives,
    crossshare,
    determinism,
    devicescope,
    donation,
    dtypedomain,
    excepts,
    flags,
    lifecycle,
    lockorder,
    partition,
    prints,
    purity,
    races,
    scalingmath,
    shardresource,
    statichash,
    tracesafety,
    twinparity,
    wirecontract,
)
from rtap_tpu.analysis.core import (  # noqa: F401
    AnalysisContext,
    Baseline,
    Finding,
    Report,
    SourceFile,
    run_analysis,
)

#: execution order: cheap syntactic passes first, then the
#: interprocedural per-class pass, then the whole-program v2 passes,
#: then the device-kernel v3 family (ordering is cosmetic — every pass
#: always runs). Each pass declares PARTITION = "file" (findings
#: depend only on one file's bytes — eligible for warm-cache per-file
#: reuse) or "program" (cross-file inputs — all-or-nothing). NB: the
#: name SCOPE is already taken in several pass modules for their
#: path-prefix tuples — core.py reads PARTITION, nothing else.
PASSES = (prints, excepts, flags, purity, races,
          determinism, lifecycle, lockorder, crossshare,
          tracesafety, statichash, dtypedomain,
          twinparity, donation, wirecontract,
          devicescope, collectives, shardresource,
          partition, scalingmath)

#: rule id -> description, across every pass (the CLI's --list-passes)
ALL_RULES = {rid: desc for mod in PASSES for rid, desc in mod.RULES.items()}
