"""The whole-program model the cross-module passes share (ISSUE 13).

PR 11's passes were per-class by design: every fact they needed lived
inside one ``ClassDef``. The v2 passes (lock-order, cross-share) reason
about facts that only exist BETWEEN classes — which collaborator an
attribute holds, which classes run code on their own threads, who
constructs what and hands it to whom. This module builds that model
once per analysis run and memoizes it on the context:

* a **class registry** over every scope file (name -> :class:`ClassInfo`
  with methods, lock attributes, thread-spawn evidence);
* **collaborator typing**: ``self.attr -> {candidate class names}``,
  resolved three ways — direct construction (``self.x = Tracker(...)``),
  annotated ``__init__`` params (``tracker: HealthTracker``) stored to
  attrs, and call-site inference (every ``C(...)`` construction in the
  program matched to ``C.__init__``'s params, with argument expressions
  resolved through same-function locals). Candidates are SETS — an
  ambiguous name keeps every candidate, because a may-analysis that
  guessed one would silently drop real deadlock edges;
* **construction/handoff sites**: for every function in the program,
  locals bound to known-class constructors and the calls each local is
  later handed to — the ``health = HealthTracker(...)`` /
  ``ExpositionServer(health=health)`` / ``live_loop(..., health=health)``
  wiring the cross-share pass exists to see.

Everything here is pure AST: no imports are resolved, classes are keyed
by bare name. The repo has no duplicate public class names across the
serve stack; if one ever appears, the FIRST definition in sorted-path
discovery order wins the registry slot (deterministic — discovery
sorts both dirs and files) and the per-class passes still analyze
every definition. A collision therefore narrows the whole-program
model rather than corrupting it; renaming the newcomer is the fix.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from rtap_tpu.analysis.core import AnalysisContext

__all__ = ["ClassInfo", "ConstructedLocal", "Program", "build_program"]

#: lock-ish constructors: ``self.x = threading.Lock()`` makes x a lock
#: attribute; RLock/Condition(RLock) are re-entrant (self-edges legal)
_LOCK_CTORS = {"Lock": False, "RLock": True, "Condition": True,
               "Semaphore": False, "BoundedSemaphore": False}


def dotted(node: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def is_thread_ctor(call: ast.Call) -> bool:
    d = dotted(call.func)
    return d in ("threading.Thread", "Thread", "threading.Timer", "Timer")


@dataclass
class ClassInfo:
    """Everything the cross-module passes need to know about one class."""

    name: str
    path: str               # repo-relative posix path of the defining file
    node: ast.ClassDef
    methods: dict[str, ast.FunctionDef] = field(default_factory=dict)
    #: self attrs assigned a lock constructor -> reentrant?
    lock_attrs: dict[str, bool] = field(default_factory=dict)
    #: self attrs holding collaborators -> candidate class names
    collab_attrs: dict[str, set[str]] = field(default_factory=dict)
    #: the class spawns threads (Thread/Timer ctor anywhere in a method,
    #: or subclasses a Threading* server) — the cross-share pass's
    #: "runs code on its own thread" side
    spawns_thread: bool = False

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{attr}"


@dataclass
class ConstructedLocal:
    """One ``v = KnownClass(...)`` local + everywhere v is handed on."""

    var: str
    cls: str                # constructed class name
    path: str
    line: int
    func_qual: str          # qualname of the constructing function
    #: callables this local was passed INTO (dotted callee names)
    consumers: list[str] = field(default_factory=list)
    #: methods invoked directly on the local (``v.m()``)
    direct_calls: list[str] = field(default_factory=list)


@dataclass
class Program:
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    constructed: list[ConstructedLocal] = field(default_factory=list)

    def resolve(self, name: str) -> ClassInfo | None:
        return self.classes.get(name)


def _functions(tree: ast.AST):
    """(qualname, node) for every function/method, outer-first."""
    out = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child))
                walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def _classes_in(tree: ast.AST):
    """Every ClassDef, including nested ones (handler classes)."""
    return [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]


def _own_body_nodes(fn: ast.FunctionDef):
    """Walk a function's body IN SOURCE ORDER, excluding nested
    function/class defs — those are yielded by _functions under their
    own qualnames, and walking them twice would double-record
    constructions with the wrong enclosing scope. Order matters: the
    construction sweep must see ``v = C()`` before v's consumers."""
    def rec(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            yield child
            yield from rec(child)

    for st in fn.body:
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            continue
        yield st
        yield from rec(st)


def _lock_ctor_kind(value: ast.AST) -> bool | None:
    """reentrant? for a lock-constructor value expression, else None."""
    if not isinstance(value, ast.Call):
        return None
    d = dotted(value.func)
    if d is None:
        return None
    leaf = d.rsplit(".", 1)[-1]
    if leaf in _LOCK_CTORS and (d == leaf or d.startswith("threading.")):
        return _LOCK_CTORS[leaf]
    return None


def _self_attr_target(t: ast.AST, self_name: str) -> str | None:
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == self_name:
        return t.attr
    return None


def _harvest_class(ci: ClassInfo, registry: dict[str, ClassInfo]) -> None:
    """Fill lock_attrs / collab_attrs / spawns_thread for one class.
    Collaborator typing via direct construction and annotated params;
    call-site inference happens in a later whole-program sweep."""
    for base in ci.node.bases:
        d = dotted(base) or ""
        if "Threading" in d or "RequestHandler" in d:
            ci.spawns_thread = True
    for m in ci.node.body:
        if not isinstance(m, ast.FunctionDef) or not m.args.args:
            continue
        self_name = m.args.args[0].arg
        #: annotated __init__ params: name -> class name
        ann: dict[str, str] = {}
        if m.name == "__init__":
            for a in m.args.args[1:] + m.args.kwonlyargs:
                if a.annotation is not None:
                    for n in ast.walk(a.annotation):
                        nm = None
                        if isinstance(n, (ast.Name, ast.Attribute)):
                            nm = dotted(n)
                        elif isinstance(n, ast.Constant) \
                                and isinstance(n.value, str):
                            nm = n.value  # forward-ref string annotation
                        if nm and nm.rsplit(".", 1)[-1] in registry:
                            ann[a.arg] = nm.rsplit(".", 1)[-1]
        for node in ast.walk(m):
            if isinstance(node, ast.Call) and is_thread_ctor(node):
                ci.spawns_thread = True
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            if value is None:
                continue
            for t in targets:
                attr = _self_attr_target(t, self_name)
                if attr is None:
                    continue
                reent = _lock_ctor_kind(value)
                if reent is not None:
                    ci.lock_attrs[attr] = reent
                    continue
                if isinstance(value, ast.Call):
                    d = dotted(value.func)
                    leaf = d.rsplit(".", 1)[-1] if d else None
                    if leaf in registry:
                        ci.collab_attrs.setdefault(attr, set()).add(leaf)
                        continue
                if isinstance(value, ast.Name) and value.id in ann:
                    ci.collab_attrs.setdefault(attr, set()).add(
                        ann[value.id])


def _init_param_names(ci: ClassInfo) -> list[str]:
    init = ci.methods.get("__init__")
    if init is None:
        return []
    return [a.arg for a in init.args.args[1:]]


def _sweep_constructions(prog: Program, ctx: AnalysisContext) -> None:
    """Whole-program sweep: for every function, find locals bound to
    known-class constructors, where they are handed on, and — for
    constructor calls — bind argument types back onto the callee's
    ``__init__`` params (call-site collaborator inference)."""
    for sf in ctx.files:
        if sf.tree is None:
            continue
        for qual, fn in _functions(sf.tree):
            #: local name -> constructed class name (last binding wins;
            #: good enough for the linear wiring code this models)
            local_types: dict[str, str] = {}
            records: dict[str, ConstructedLocal] = {}
            for node in _own_body_nodes(fn):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    d = dotted(node.value.func)
                    leaf = d.rsplit(".", 1)[-1] if d else None
                    if leaf in prog.classes:
                        for t in node.targets:
                            if isinstance(t, ast.Name):
                                local_types[t.id] = leaf
                                records[t.id] = ConstructedLocal(
                                    var=t.id, cls=leaf, path=sf.path,
                                    line=node.lineno, func_qual=qual)
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted(node.func)
                if callee is None:
                    continue
                leaf = callee.rsplit(".", 1)[-1]
                callee_ci = prog.classes.get(leaf)
                # ---- handoff tracking --------------------------------
                handed = []
                for a in node.args:
                    if isinstance(a, ast.Name):
                        handed.append((None, a.id))
                for kw in node.keywords:
                    if isinstance(kw.value, ast.Name) and kw.arg:
                        handed.append((kw.arg, kw.value.id))
                for _slot, name in handed:
                    if name in records:
                        records[name].consumers.append(callee)
                # v.m(...) — the constructing scope itself uses v
                if isinstance(node.func, ast.Attribute) \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in records:
                    records[node.func.value.id].direct_calls.append(
                        node.func.attr)
                # ---- call-site param typing --------------------------
                if callee_ci is None:
                    continue
                params = _init_param_names(callee_ci)
                init = callee_ci.methods.get("__init__")
                kwonly = {a.arg for a in init.args.kwonlyargs} \
                    if init is not None else set()

                def _type_of(expr) -> str | None:
                    if isinstance(expr, ast.Call):
                        d2 = dotted(expr.func)
                        lf = d2.rsplit(".", 1)[-1] if d2 else None
                        return lf if lf in prog.classes else None
                    if isinstance(expr, ast.Name):
                        return local_types.get(expr.id)
                    return None

                bindings: dict[str, str] = {}
                for i, a in enumerate(node.args):
                    ty = _type_of(a)
                    if ty is not None and i < len(params):
                        bindings[params[i]] = ty
                for kw in node.keywords:
                    ty = _type_of(kw.value)
                    if ty is not None and kw.arg \
                            and (kw.arg in params or kw.arg in kwonly):
                        bindings[kw.arg] = ty
                if not bindings:
                    continue
                # park param->type on the callee: any __init__ body
                # ``self.x = <param>`` adopts the binding
                if init is not None:
                    self_name = init.args.args[0].arg \
                        if init.args.args else "self"
                    for st in ast.walk(init):
                        if isinstance(st, ast.Assign) \
                                and isinstance(st.value, ast.Name) \
                                and st.value.id in bindings:
                            for t in st.targets:
                                attr = _self_attr_target(t, self_name)
                                if attr is not None:
                                    callee_ci.collab_attrs.setdefault(
                                        attr, set()).add(
                                            bindings[st.value.id])
            prog.constructed.extend(records.values())


def build_program(ctx: AnalysisContext) -> Program:
    """Build (or return the memoized) whole-program model for this
    context. Memoized on the context object: lock-order and cross-share
    both consume it and the model must be built exactly once per run."""
    cached = getattr(ctx, "_program", None)
    if cached is not None:
        return cached
    prog = Program()
    for sf in ctx.files:
        if sf.tree is None:
            continue
        for cls in _classes_in(sf.tree):
            ci = ClassInfo(name=cls.name, path=sf.path, node=cls)
            ci.methods = {n.name: n for n in cls.body
                          if isinstance(n, ast.FunctionDef)}
            # first definition wins; later same-name classes still get
            # analyzed per-file by the per-class passes
            prog.classes.setdefault(cls.name, ci)
    for ci in prog.classes.values():
        _harvest_class(ci, prog.classes)
    _sweep_constructions(prog, ctx)
    ctx._program = prog
    return prog
