"""Shard-scoped resources: two shards must never clobber one file.

Rule ``shard-resource`` (ISSUE 15) — the journal dir, the checkpoint
group claims, the lease file, and the alert/corr sidecars are all
per-serve-process state. Run two shard processes of ROADMAP-1's mesh
against the same operator paths and every one of them becomes a silent
split-brain: interleaved journal segments, a lease two leaders both
think they hold, a correlator sidecar floor ping-ponging between two
folds. The fix discipline is ONE shard-qualified helper —
``service/shardpath.py`` (``shard_scoped_path`` / ``group_checkpoint_
path`` / ``alert_sidecar_path``; shard 0 is byte-identical to the
pre-mesh paths) — and this pass makes bypassing it a finding:

* ``<qual>:mint`` — a resource path minted by bare string construction
  (``path + ".corr"``, ``f"group{gi:04d}"`` joins, sidecar suffixes in
  f-strings) anywhere outside shardpath.py: only the helper may spell
  these suffixes, so a new call site cannot forget the shard;
* ``<qual>:inline-path:<Class>`` — a ``TickJournal``/``Lease``/
  ``AlertWriter`` constructed over an inline path expression instead
  of a helper-bound name (the concat hazard at the construction site
  itself);
* ``serve-wiring:<flag>`` — the serve CLI (rtap_tpu/__main__.py) wires
  an operator resource flag (``--journal-dir``/``--checkpoint-dir``/
  ``--lease-file``/``--alerts``) without routing it through
  ``shard_scoped_path`` (the zero-cost rebind that makes every
  downstream path shard-correct the day the shard index is nonzero).
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.kernels import dotted, own_body_nodes
from rtap_tpu.analysis.meshmodel import build_mesh_model, functions_of

PASS_NAME = "shard-resource"
PARTITION = "file"
RULES = {
    "shard-resource": "shard-scoped resource paths (journal dir, "
                      "checkpoint claims, lease file, alert sidecars) "
                      "minted outside service/shardpath.py or wired "
                      "past it",
}

#: the one helper module allowed to spell resource suffixes
HELPER_PATH = "rtap_tpu/service/shardpath.py"

#: the helpers a constructor-site path expression may call directly
HELPER_FNS = frozenset({"shard_scoped_path", "group_checkpoint_path",
                        "alert_sidecar_path"})

#: serve flags whose values are shard-scoped resources (attr names on
#: the parsed argparse namespace)
SERVE_RESOURCE_FLAGS = ("journal_dir", "checkpoint_dir", "lease_file",
                        "alerts")

_MAIN_PATH = "rtap_tpu/__main__.py"


def _scoped_expr(node: ast.AST) -> bool:
    """True when a constructor's path argument is an opaque binding
    (responsibility chained to the caller) or a direct helper call —
    never an inline concat/f-string/join minted at the site."""
    if isinstance(node, (ast.Name, ast.Attribute, ast.Subscript,
                         ast.Constant)):
        return True
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        leaf = d.rsplit(".", 1)[-1] if d else None
        if leaf in HELPER_FNS:
            return True
        # Path(x) / str(x) wrappers around an opaque binding stay opaque
        if leaf in ("Path", "str") and len(node.args) == 1:
            return _scoped_expr(node.args[0])
    return False


def run(ctx: AnalysisContext) -> list[Finding]:
    model = build_mesh_model(ctx)
    out: list[Finding] = []
    for site in model.resources:
        if site.path == HELPER_PATH:
            continue   # the helper owns the suffixes by design
        if site.kind == "mint":
            out.append(Finding(
                rule="shard-resource", path=site.path, line=site.line,
                symbol=f"{site.qual}:mint",
                message=f"resource path minted by bare string "
                        f"construction ({site.detail}) — only "
                        "service/shardpath.py may spell shard-scoped "
                        "suffixes/claims; route through "
                        "shard_scoped_path/group_checkpoint_path/"
                        "alert_sidecar_path so a second shard can "
                        "never clobber this file"))
        elif site.node is not None and not _scoped_expr(site.node):
            out.append(Finding(
                rule="shard-resource", path=site.path, line=site.line,
                symbol=f"{site.qual}:inline-path:{site.kind}",
                message=f"{site.kind} constructed over an inline path "
                        "expression — bind the path through a "
                        "service/shardpath helper (or an opaque "
                        "parameter the caller scoped) first"))

    # ---- serve CLI wiring: every resource flag passes the helper -----
    main = ctx.file(_MAIN_PATH)
    if main is not None and main.tree is not None:
        used = {f for f in SERVE_RESOURCE_FLAGS
                if f"args.{f}" in main.text}
        covered: set[str] = set()
        for qual, fn in functions_of(main):
            calls_helper = any(
                isinstance(n, ast.Call)
                and (dotted(n.func) or "").rsplit(".", 1)[-1]
                == "shard_scoped_path"
                for n in own_body_nodes(fn))
            if not calls_helper:
                continue
            for n in own_body_nodes(fn):
                if isinstance(n, ast.Constant) \
                        and isinstance(n.value, str) \
                        and n.value in SERVE_RESOURCE_FLAGS:
                    covered.add(n.value)
                elif isinstance(n, ast.Attribute) \
                        and n.attr in SERVE_RESOURCE_FLAGS:
                    covered.add(n.attr)
        for flag in sorted(used - covered):
            out.append(Finding(
                rule="shard-resource", path=_MAIN_PATH, line=1,
                symbol=f"serve-wiring:{flag}",
                message=f"serve wires args.{flag} without routing it "
                        "through shard_scoped_path — the operator path "
                        "reaches a shard-scoped resource un-scoped "
                        "(shard 0 is byte-identical, so the rebind is "
                        "free today and correct on the mesh)"))
    return out
