"""SARIF 2.1.0 output: findings rendered where reviewers live.

``python -m rtap_tpu.analysis --sarif PATH`` writes one standard SARIF
log beside the existing ``--json`` artifact line (which keeps its
one-line stdout contract untouched — SARIF goes to a file). SARIF is
what CI annotators and editors already speak: the same findings the
gate prints as ``path:line: [rule] symbol: message`` become inline PR
annotations and editor squiggles with zero glue.

Mapping choices (shape-pinned by tests/unit/test_static_checks.py):

* every rule (plus the synthetic ``parse-error``) becomes a
  ``tool.driver.rules`` entry, so viewers can render rule metadata;
* unsuppressed findings are ``level: error`` results — the gate's
  subject, exactly what ``ok`` is false about;
* inline-suppressed and baselined findings are emitted too, carrying a
  ``suppressions`` entry (``kind: inSource`` for ``# rtap: allow``
  comments, ``kind: external`` for ``analysis_baseline.json``) so a
  viewer shows them greyed out instead of not at all — an auditor can
  SEE the tolerances without reading the baseline file;
* the stable ``(rule, path, symbol)`` key rides in
  ``partialFingerprints`` so result tracking survives line drift, same
  property the baseline relies on.
"""

from __future__ import annotations

from rtap_tpu.analysis.core import Finding, Report

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def _result(f: Finding, level: str,
            suppression_kind: str | None) -> dict:
    out = {
        "ruleId": f.rule,
        "level": level,
        "message": {"text": f"{f.symbol}: {f.message}"},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(f.line, 1)},
            }
        }],
        "partialFingerprints": {
            "rtapLintKey/v1": f"{f.rule}:{f.path}:{f.symbol}",
        },
    }
    if suppression_kind is not None:
        out["suppressions"] = [{"kind": suppression_kind}]
    return out


def to_sarif(report: Report) -> dict:
    """One SARIF 2.1.0 log for one analyzer run."""
    from rtap_tpu.analysis import ALL_RULES

    rules = dict(ALL_RULES)
    rules["parse-error"] = "file failed to parse (the analyzer " \
        "degrades loudly, never silently skips)"
    results = [_result(f, "error", None) for f in report.findings]
    results += [_result(f, "note", "inSource") for f in report.suppressed]
    results += [_result(f, "note", "external") for f in report.baselined]
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "rtap-lint",
                    "informationUri":
                        "docs/ANALYSIS.md",
                    "rules": [
                        {"id": rid,
                         "shortDescription": {"text": desc}}
                        for rid, desc in sorted(rules.items())
                    ],
                }
            },
            "columnKind": "utf16CodeUnits",
            "results": results,
            "properties": {
                "filesScanned": report.files_scanned,
                "cache": report.cache_mode,
                "perPass": dict(sorted(report.per_pass.items())),
            },
        }],
    }
