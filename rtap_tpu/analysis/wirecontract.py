"""Wire contract: struct formats and type codes never drift from docs.

Rule ``wire-contract`` — the flag-docs gate's binary dual (ISSUE 14).
The RB1 ingest frame (ingest/protocol.py ↔ docs/INGEST.md) and the RJ
journal record (resilience/journal.py ↔ docs/RESILIENCE.md) are
FROZEN framings: producers, soak feeders, and recovery code on other
machines parse them from the operator docs. A struct format string
edited without its doc row (or vice versa) is a silent cross-version
corruption; this pass cross-checks them statically.

What is extracted from every ``rtap_tpu/ingest/`` / ``rtap_tpu/
resilience/`` file (pure AST + the assignment's trailing comment,
which names the fields — the same comment-as-contract idiom as
suppressions):

* ``NAME = struct.Struct("<fmt>")  # field, names`` — per-field
  offsets/sizes computed from the format chars;
* ``*MAGIC = b"..."`` constants;
* type-code groups: a tuple ``_TYPES``/``_KINDS`` of Name constants
  (``KIND_DATA``...), each resolved to its int value.

Checks (symbols are line-insensitive; docs text = README + docs/*.md):

* format strings must be explicit little-endian (``<``) — wire layout
  may never depend on host alignment;
* the comment must name exactly as many fields as the format has;
* magics are unique AND prefix-free across the framings (a magic that
  prefixes another breaks byte-wise resync);
* type codes are unique within their group, and each code's doc token
  (``DATA``, ``TICK``) must co-occur with its numeric value on some
  doc line (``1=DATA``, ``TICK (1)``);
* a *header* struct (format opens with the magic's ``Ns``) must match
  its doc layout: a markdown ``| offset | size | field |`` table whose
  magic row names the magic (per-field offset+size equality, every
  field documented), or an inline ``b"RJ" | type u8 | len u32`` line
  (width-sequence equality). Neither present = undocumented framing;
* any other comment-named field mentioned in docs as ``<name> <width>``
  (``tick i64``) must agree on width.
"""

from __future__ import annotations

import ast
import re

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.kernels import dotted

PASS_NAME = "wire-contract"
PARTITION = "program"
RULES = {
    "wire-contract": "struct format / type code / magic drifted from "
                     "the documented wire layout (docs/INGEST.md, "
                     "docs/RESILIENCE.md)",
}

_SCOPES = ("rtap_tpu/ingest/", "rtap_tpu/resilience/")

#: struct format char -> byte width ('s' handled via its repeat count)
_CHAR_SIZES = {"x": 1, "c": 1, "b": 1, "B": 1, "?": 1, "h": 2, "H": 2,
               "e": 2, "i": 4, "I": 4, "l": 4, "L": 4, "f": 4,
               "q": 8, "Q": 8, "d": 8}

#: doc width tokens -> byte width
_TOKEN_SIZES = {"u8": 1, "i8": 1, "u16": 2, "i16": 2, "u32": 4,
                "i32": 4, "f32": 4, "u64": 8, "i64": 8, "f64": 8}

_TOKEN_RE = re.compile(r"\b(u8|i8|u16|i16|u32|i32|f32|u64|i64|f64)\b")


def parse_format(fmt: str) -> list[tuple[str, int]] | None:
    """[(chars, size)] per field, or None on an unparseable format."""
    body = fmt[1:] if fmt[:1] in "<>=!@" else fmt
    out: list[tuple[str, int]] = []
    i = 0
    while i < len(body):
        j = i
        while j < len(body) and body[j].isdigit():
            j += 1
        count = int(body[i:j]) if j > i else 1
        if j >= len(body):
            return None
        ch = body[j]
        if ch == "s":
            out.append((f"{count}s", count))
        elif ch in _CHAR_SIZES:
            out.extend((ch, _CHAR_SIZES[ch]) for _ in range(count))
        else:
            return None
        i = j + 1
    return out


def _field_names(sf, line: int) -> list[str]:
    """Comma-separated field names from the assignment line's trailing
    comment plus directly-following comment-only lines."""
    chunks: list[str] = []
    ln = sf.lines[line - 1] if line - 1 < len(sf.lines) else ""
    if "#" in ln:
        chunks.append(ln.split("#", 1)[1])
    nxt = line
    # a continuation is only consumed while the list is visibly OPEN
    # (accumulated text ends with a comma — the protocol.py idiom);
    # otherwise the next comment is unrelated prose, and swallowing it
    # would corrupt the field map into a spurious red gate. `#:` lines
    # document the NEXT binding and never continue the list.
    while nxt < len(sf.lines) and " ".join(chunks).rstrip().endswith(","):
        stripped = sf.lines[nxt].lstrip()
        if not stripped.startswith("#") or stripped.startswith("#:"):
            break
        chunks.append(stripped[1:])
        nxt += 1
    text = " ".join(chunks)
    return [t.strip() for t in text.split(",") if t.strip()]


def _tables(docs: str) -> list[dict]:
    """Markdown | offset | size | field | tables -> list of
    {'rows': {field: (offset, size)}, 'text': full table text}."""
    out = []
    lines = docs.splitlines()
    i = 0
    while i < len(lines):
        cells = [c.strip().lower() for c in lines[i].split("|")]
        if "offset" in cells and "size" in cells and "field" in cells:
            cols = {name: cells.index(name)
                    for name in ("offset", "size", "field")}
            rows: dict[str, tuple[int, int]] = {}
            text = [lines[i]]
            j = i + 1
            while j < len(lines) and lines[j].lstrip().startswith("|"):
                text.append(lines[j])
                row = [c.strip() for c in lines[j].split("|")]
                if len(row) > max(cols.values()):
                    off, size = row[cols["offset"]], row[cols["size"]]
                    field = row[cols["field"]].strip("`* ")
                    if off.isdigit() and size.isdigit() and field:
                        rows[field] = (int(off), int(size))
                j += 1
            out.append({"rows": rows, "text": "\n".join(text)})
            i = j
        else:
            i += 1
    return out


def _magic_doc_line(docs: str, magic: str) -> list[int] | None:
    """Width sequence from an inline ``b"RJ" | type u8 | len u32``
    style doc line, or None when no such line exists."""
    for line in docs.splitlines():
        if magic in line and "|" in line:
            widths = [_TOKEN_SIZES[t] for t in _TOKEN_RE.findall(line)]
            if widths:
                return widths
    return None


def run(ctx: AnalysisContext) -> list[Finding]:
    docs = ctx.docs()
    out: list[Finding] = []
    magics: dict[str, tuple[str, str, int]] = {}  # ascii -> (path, name, line)

    for sf in ctx.files_under(*_SCOPES):
        if sf.tree is None:
            continue
        structs: dict[str, tuple[str, int]] = {}   # name -> (fmt, line)
        consts: dict[str, int] = {}
        groups: dict[str, tuple[list[str], int]] = {}
        file_magics: list[tuple[str, str, int]] = []
        for node in sf.tree.body:
            if not isinstance(node, ast.Assign) \
                    or len(node.targets) != 1 \
                    or not isinstance(node.targets[0], ast.Name):
                continue
            name = node.targets[0].id
            v = node.value
            if isinstance(v, ast.Call) \
                    and dotted(v.func) in ("struct.Struct", "Struct") \
                    and v.args and isinstance(v.args[0], ast.Constant) \
                    and isinstance(v.args[0].value, str):
                structs[name] = (v.args[0].value, node.lineno)
            elif isinstance(v, ast.Constant) and isinstance(v.value, int) \
                    and not isinstance(v.value, bool):
                consts[name] = v.value
            elif isinstance(v, ast.Constant) \
                    and isinstance(v.value, bytes) \
                    and name.strip("_").endswith("MAGIC"):
                file_magics.append(
                    (v.value.decode("ascii", "replace"), name,
                     node.lineno))
            elif isinstance(v, ast.Tuple) \
                    and name.strip("_").upper().endswith(
                        ("KINDS", "TYPES")) \
                    and v.elts and all(isinstance(e, ast.Name)
                                       for e in v.elts):
                groups[name] = ([e.id for e in v.elts], node.lineno)

        # ---- magic uniqueness / prefix-freedom across framings ------
        for magic, name, line in file_magics:
            for seen, (spath, sname, _sline) in magics.items():
                if magic == seen or magic.startswith(seen) \
                        or seen.startswith(magic):
                    out.append(Finding(
                        rule="wire-contract", path=sf.path, line=line,
                        symbol=f"magic:{magic}",
                        message=f"magic {magic!r} ({name}) collides "
                                f"with {sname} {seen!r} ({spath}) — "
                                "framings must be unique and "
                                "prefix-free or byte-wise resync "
                                "misparses one as the other"))
            magics.setdefault(magic, (sf.path, name, line))

        # ---- type-code groups ---------------------------------------
        for gname, (members, line) in groups.items():
            values = {m: consts.get(m) for m in members}
            by_val: dict[int, str] = {}
            for m, val in values.items():
                if val is None:
                    continue
                if val in by_val:
                    out.append(Finding(
                        rule="wire-contract", path=sf.path, line=line,
                        symbol=f"code:{m}",
                        message=f"type code {m}={val} duplicates "
                                f"{by_val[val]} in {gname} — the "
                                "walker cannot dispatch on an "
                                "ambiguous code"))
                    continue
                by_val[val] = m
                token = m.rsplit("_", 1)[-1]
                documented = any(
                    token in ln and re.search(rf"\b{val}\b", ln)
                    for ln in docs.splitlines())
                if not documented:
                    out.append(Finding(
                        rule="wire-contract", path=sf.path, line=line,
                        symbol=f"code:{m}",
                        message=f"type code {m}={val} is not "
                                "documented (no doc line pairs "
                                f"'{token}' with {val}) — the wire "
                                "docs are the cross-version parser "
                                "contract"))

        # ---- struct formats vs docs ---------------------------------
        for sname, (fmt, line) in structs.items():
            fields = parse_format(fmt)
            if fields is None:
                out.append(Finding(
                    rule="wire-contract", path=sf.path, line=line,
                    symbol=f"fmt:{sname}",
                    message=f"unparseable struct format {fmt!r}"))
                continue
            if fmt[:1] != "<":
                out.append(Finding(
                    rule="wire-contract", path=sf.path, line=line,
                    symbol=f"fmt:{sname}:endian",
                    message=f"struct format {fmt!r} is not explicit "
                            "little-endian ('<') — wire layout must "
                            "not depend on host alignment"))
            names = _field_names(sf, line)
            if names and len(names) != len(fields):
                out.append(Finding(
                    rule="wire-contract", path=sf.path, line=line,
                    symbol=f"fmt:{sname}:names",
                    message=f"{sname} comment names {len(names)} "
                            f"fields but format {fmt!r} has "
                            f"{len(fields)} — the comment IS the "
                            "field map; keep it exact"))
                names = []
            if not names:
                continue
            # header struct: its comment NAMES the magic field and the
            # format opens with that magic's Ns (first-field length
            # alone would misclassify an unrelated `<2sI` trailer as
            # the framing header and fail it against the wrong table)
            magic_here = next(
                (m for m, n, _l in file_magics
                 if names[0].lower() == "magic"
                 and fields[0][0] == f"{len(m)}s"), None)
            if magic_here is not None:
                out.extend(_check_header(
                    sf, sname, line, fields, names, magic_here, docs))
            else:
                out.extend(_check_inline_widths(
                    sf, sname, line, fields, names, docs))
    return out


def _offsets(fields: list[tuple[str, int]]) -> list[int]:
    offs, total = [], 0
    for _ch, size in fields:
        offs.append(total)
        total += size
    return offs


def _check_header(sf, sname, line, fields, names, magic, docs):
    out: list[Finding] = []
    offs = _offsets(fields)
    table = next((t for t in _tables(docs)
                  if magic in t["text"]), None)
    if table is not None:
        for fname, (ch, size), off in zip(names, fields, offs):
            doc = table["rows"].get(fname)
            if doc is None:
                out.append(Finding(
                    rule="wire-contract", path=sf.path, line=line,
                    symbol=f"{sname}.{fname}:undocumented",
                    message=f"header field {fname} has no row in the "
                            f"{magic} layout table — document offset "
                            f"{off}, size {size}"))
            elif doc != (off, size):
                out.append(Finding(
                    rule="wire-contract", path=sf.path, line=line,
                    symbol=f"{sname}.{fname}",
                    message=f"header field {fname} is offset {off} "
                            f"size {size} in {sname} ({fields!r}) but "
                            f"the {magic} doc table says offset "
                            f"{doc[0]} size {doc[1]} — struct and doc "
                            "drifted; fix whichever is wrong and bump "
                            "the magic if the wire layout changed"))
        return out
    widths = _magic_doc_line(docs, magic)
    if widths is None:
        out.append(Finding(
            rule="wire-contract", path=sf.path, line=line,
            symbol=f"{sname}:undocumented",
            message=f"framing {sname} (magic {magic!r}) has neither a "
                    "doc layout table nor an inline width line — the "
                    "wire docs are the cross-version parser contract"))
        return out
    struct_widths = [size for _ch, size in fields[1:]]
    for i, w in enumerate(widths):
        if i < len(struct_widths) and w != struct_widths[i]:
            out.append(Finding(
                rule="wire-contract", path=sf.path, line=line,
                symbol=f"{sname}.{names[i + 1]}",
                message=f"field {names[i + 1]} is {struct_widths[i]} "
                        f"bytes in {sname} but the {magic} doc line "
                        f"says {w} — struct and doc drifted"))
    return out


def _check_inline_widths(sf, sname, line, fields, names, docs):
    """Non-header structs: any field documented as `<name> <width>`
    must agree."""
    out: list[Finding] = []
    for fname, (_ch, size) in zip(names, fields):
        if not re.fullmatch(r"\w+", fname):
            continue
        m = re.search(rf"\b{fname}\s+"
                      r"(u8|i8|u16|i16|u32|i32|f32|u64|i64|f64)\b",
                      docs)
        if m and _TOKEN_SIZES[m.group(1)] != size:
            out.append(Finding(
                rule="wire-contract", path=sf.path, line=line,
                symbol=f"{sname}.{fname}",
                message=f"field {fname} is {size} bytes in {sname} "
                        f"but documented as {m.group(1)} — struct and "
                        "doc drifted"))
    return out
