"""Hot-path purity: determinism, no device fetches, not-NaN presence.

Three rules over the fused-step kernels (``rtap_tpu/ops/``) and the
live-loop tick path (``rtap_tpu/service/loop.py``):

``purity-nondet`` — host nondeterminism inside device code. A fused
step that reads ``time.time()``, ``random``, or an argless
``datetime.now()`` cannot be bit-exact against its oracle twin, and the
journal's replay contract (bit-identical resume) dies with it. In
``ops/`` every wall-clock/random source is forbidden; in ``loop.py``
the wall clock IS the pacer (cadence sleeps, deadline accounting) so
only the genuinely nondeterministic sources (random, datetime.now,
uuid, secrets) are forbidden — timestamps entering scoring must come
from the SOURCE clock (the monotonic clamp), never be minted mid-path.

``purity-fetch`` — device→host fetches inside kernel code. A function
in ``ops/`` that traces with ``jnp``/``lax`` must not call
``np.asarray``/``np.array``/``.item()``/``jax.device_get`` on its
values: under jit that is a concrete-value fetch (TracerError at best,
a silent sync at worst). Host-side twins (pure-numpy functions) are out
of scope by construction — the rule only fires inside functions that
also touch ``jnp``/``lax``.

``purity-isfinite`` — presence checks in the wire/journal/sink layer.
The repo contract is presence == not-NaN: a producer may push ``inf``
(legal f32) and it must survive ingest merges, journal frame synthesis,
and replay bit-exactly (the PR 7 class of bug: ``isfinite`` silently
turned a wire inf into a missing sample on one path and not another,
breaking journal bit-exactness). ``isfinite`` is forbidden in
``ingest/``, ``resilience/``, ``correlate/`` and the serve loop/source/
sink modules; model-layer encoders (``ops/``, ``models/``) keep their
deliberate isfinite semantics — both twins implement it identically.
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding

PASS_NAME = "purity"
#: findings depend only on one file's bytes -> the warm
#: cache may replay them per file (core.py partition contract)
PARTITION = "file"
RULES = {
    "purity-nondet": "host nondeterminism (time/random/datetime.now) in "
                     "device-kernel or tick-path code",
    "purity-fetch": "device->host fetch (np.asarray/.item()/device_get) "
                    "inside a jnp/lax-tracing function in ops/",
    "purity-isfinite": "isfinite presence check where the wire/journal "
                       "contract is not-NaN (inf must survive replay)",
}

#: wall-clock reads — banned in ops/ (twins must replay), legitimate in
#: loop.py (the pacer), where only the _nondet_reason sources apply
_TIME_CALLS = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
})

_ISFINITE_SCOPE = (
    "rtap_tpu/ingest/", "rtap_tpu/resilience/", "rtap_tpu/correlate/",
    "rtap_tpu/service/loop.py", "rtap_tpu/service/sources.py",
    "rtap_tpu/service/alerts.py",
)

_FETCH_CALLS = frozenset({
    "np.asarray", "np.array", "np.asanyarray", "numpy.asarray",
    "numpy.array", "jax.device_get",
})


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _nondet_reason(call: ast.Call, allow_time: bool) -> str | None:
    d = _dotted(call.func)
    if d is None:
        return None
    if not allow_time and d in _TIME_CALLS:
        return f"{d}() — the device/oracle twins cannot replay a wall " \
               "clock; thread timestamps in from the caller"
    if d == "random" or d.startswith("random.") or ".random." in d \
            or d.endswith(".random") or d.startswith("np.random") \
            or d.startswith("numpy.random"):
        # jax.random is keyed/deterministic and exempt
        if d.startswith("jax.random"):
            return None
        return f"{d}() — unseeded host randomness breaks bit-exact " \
               "twins and journal replay; use a keyed jax.random or " \
               "seed threaded from config"
    if d.endswith("datetime.now") or d.endswith("datetime.utcnow") \
            or d.endswith("date.today"):
        if not call.args:
            return f"{d}() — an argless now() mints a nondeterministic " \
                   "timestamp mid-path; use the row's source ts"
    if d == "os.urandom" or d.startswith("uuid.") \
            or d.startswith("secrets."):
        return f"{d}() — nondeterministic identity on the hot path"
    return None


def _functions(tree: ast.AST):
    """(qualname, FunctionDef) for every function/method, outer-first."""
    out = []

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out.append((q, child))
                walk(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                walk(child, f"{prefix}{child.name}.")
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


def _own_body_nodes(fn: ast.FunctionDef):
    """Walk a function's body excluding nested function/class defs
    (those are reported under their own qualnames)."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _uses_tracing(fn: ast.FunctionDef) -> bool:
    for node in _own_body_nodes(fn):
        if isinstance(node, ast.Name) and node.id in ("jnp", "lax"):
            return True
        if isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d and (d.startswith("jnp.") or d.startswith("lax.")
                      or d.startswith("jax.lax.")):
                return True
    return False


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []

    # ---- ops/: nondeterminism + device fetches -----------------------
    for sf in ctx.files_under("rtap_tpu/ops/"):
        if sf.tree is None:
            continue
        for qual, fn in _functions(sf.tree):
            tracing = _uses_tracing(fn)
            for node in _own_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                reason = _nondet_reason(node, allow_time=False)
                if reason is not None:
                    out.append(Finding(
                        rule="purity-nondet", path=sf.path,
                        line=node.lineno, symbol=qual, message=reason))
                if tracing:
                    d = _dotted(node.func)
                    if d in _FETCH_CALLS:
                        out.append(Finding(
                            rule="purity-fetch", path=sf.path,
                            line=node.lineno, symbol=qual,
                            message=f"{d}() inside a jnp/lax-tracing "
                                    "function — a device->host fetch "
                                    "under jit; keep kernel values on "
                                    "device (jnp.asarray) or move the "
                                    "conversion to the host boundary"))
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr == "item" \
                            and not node.args and not node.keywords:
                        out.append(Finding(
                            rule="purity-fetch", path=sf.path,
                            line=node.lineno, symbol=qual,
                            message=".item() inside a jnp/lax-tracing "
                                    "function — a synchronous device "
                                    "fetch; return the array and let "
                                    "the host boundary convert"))

    # ---- loop.py tick path: genuine nondeterminism only --------------
    loop = ctx.file("rtap_tpu/service/loop.py")
    if loop is not None and loop.tree is not None:
        for qual, fn in _functions(loop.tree):
            for node in _own_body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                reason = _nondet_reason(node, allow_time=True)
                if reason is not None:
                    out.append(Finding(
                        rule="purity-nondet", path=loop.path,
                        line=node.lineno, symbol=qual, message=reason))

    # ---- wire/journal/sink layer: presence == not-NaN ----------------
    for sf in ctx.files_under(*_ISFINITE_SCOPE):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "isfinite":
                out.append(Finding(
                    rule="purity-isfinite", path=sf.path,
                    line=node.lineno, symbol="isfinite",
                    message="presence checks in the ingest/journal/sink "
                            "layer are not-NaN, never isfinite: a wire "
                            "inf is a legal value and must survive "
                            "merges, frame synthesis, and replay "
                            "bit-exactly (use ~np.isnan)"))
    return out
