"""Replay determinism: no iteration-order-dependent serialized output.

Rule ``replay-determinism`` — the static dual of the bit-exactness
soaks. The durability contracts (bit-identical journal replay,
byte-equal standby mirrors, exactly-once alert splice) die the moment a
serialization or hashing path iterates something whose order the
runtime does not pin:

* **set iteration** — ``for x in self._seen:`` where ``_seen`` is a
  ``set``: CPython randomizes str hashes per process, so two runs (or a
  leader and its standby) emit different orders. Wrap in ``sorted()``.
* **directory listings** — ``os.listdir`` / ``glob.glob`` /
  ``Path.iterdir`` / ``os.scandir`` order is filesystem-arbitrary; a
  recovery or checkpoint scan that folds over it unsorted can replay
  differently on two hosts. Wrap in ``sorted()``.
* **float reductions over unordered containers** — ``sum(<set>)``:
  float addition does not associate, so an order change is a VALUE
  change that survives into digests.

Scope is the serialization/hashing surface only: the journal,
checkpoints, alert sinks, replication, correlation, and the hashing
util. Model/ops code is free to iterate sets (device reductions have
their own bit-exactness tests); pulling every module in would bury the
signal this gate exists to send.

Order-insensitive folds over listings (``max`` over mtimes, membership
probes) do exist — those are suppression material with a one-line why,
not a reason to exempt the shape: the next edit to the loop body makes
the fold order-sensitive and nobody re-reviews an exempted line.

Symbols are ``<qualname>:<kind> <iterable text>`` (plus ``#n`` on
collision) — line-insensitive, so baselining survives edits.
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.program import _functions, dotted as _dotted

PASS_NAME = "replay-determinism"
#: findings depend only on one file's bytes -> the warm
#: cache may replay them per file (core.py partition contract)
PARTITION = "file"
RULES = {
    "replay-determinism": "iteration-order-dependent output in a "
                          "serialization/hashing path (unsorted set or "
                          "listdir/glob iteration, float sum over an "
                          "unordered container)",
}

#: the serialization + hashing surface (journal/checkpoint/alerts/
#: correlate/replication); the durability contracts live here
SCOPE = (
    "rtap_tpu/resilience/journal.py",
    "rtap_tpu/resilience/replicate.py",
    "rtap_tpu/service/checkpoint.py",
    "rtap_tpu/service/alerts.py",
    "rtap_tpu/correlate/",
    "rtap_tpu/utils/hashing.py",
)

#: calls whose result order is filesystem-arbitrary
_FS_LISTING = frozenset({
    "os.listdir", "listdir", "os.scandir", "scandir",
    "glob.glob", "glob.iglob",
})

#: attribute-call forms of the same (receiver-typed, name is enough)
_FS_LISTING_METHODS = frozenset({"iterdir", "glob", "rglob"})

#: sorted()/list-sort wrappers that pin an order
_ORDER_FIXERS = frozenset({"sorted", "min", "max", "len", "set",
                           "frozenset", "any", "all"})


def _is_set_expr(node: ast.AST, set_names: set[str]) -> bool:
    """The iterable is statically known to be a set."""
    if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
        return True
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d in ("set", "frozenset"):
            return True
        # a dict.keys()/.items() view ITERATED is insertion-ordered
        # (deterministic given a deterministic insert order) — not
        # flagged on its own; the BinOp branch below treats views as
        # set-like, because set OPS on them (a.keys() - b.keys())
        # return real hash-ordered sets
        return False
    if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return _is_setlike_operand(node.left, set_names) \
            or _is_setlike_operand(node.right, set_names)
    d = _dotted(node) if isinstance(node, (ast.Name, ast.Attribute)) \
        else None
    return d is not None and d in set_names


def _is_setlike_operand(node: ast.AST, set_names: set[str]) -> bool:
    """A BinOp operand that makes the whole expression a set: a set
    expression, or a dict view (``.keys()``/``.items()``) — view ops
    return real sets."""
    if _is_set_expr(node, set_names):
        return True
    return isinstance(node, ast.Call) \
        and isinstance(node.func, ast.Attribute) \
        and node.func.attr in ("keys", "items") \
        and not node.args and not node.keywords


def _set_names_in(tree: ast.AST) -> set[str]:
    """Dotted names (locals and self attrs) assigned a set anywhere in
    the file — flow-insensitive on purpose: a name that is EVER a set
    iterates as one somewhere."""
    names: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            if value is None:
                continue
            is_set = isinstance(value, (ast.Set, ast.SetComp)) or (
                isinstance(value, ast.Call)
                and _dotted(value.func) in ("set", "frozenset"))
            if not is_set:
                continue
            for t in targets:
                d = _dotted(t)
                if d is not None:
                    names.add(d)
    return names


def _fs_listing_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = _dotted(node.func)
    if d in _FS_LISTING:
        return True
    return isinstance(node.func, ast.Attribute) \
        and node.func.attr in _FS_LISTING_METHODS \
        and d not in _FS_LISTING  # path.iterdir()/glob() method forms


def _parents(tree: ast.AST) -> dict[ast.AST, ast.AST]:
    out = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def _order_pinned(node: ast.AST, parents) -> bool:
    """Some ancestor within the statement pins (or forgives) the order:
    sorted(...)/min/max/len/set()/membership, or the value is compared
    for membership (`x in listing`)."""
    cur = parents.get(node)
    while cur is not None and not isinstance(cur, ast.stmt):
        if isinstance(cur, ast.Call):
            d = _dotted(cur.func)
            if d in _ORDER_FIXERS:
                return True
        if isinstance(cur, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in cur.ops):
            return True
        cur = parents.get(cur)
    return False


def _iter_sites(fn: ast.FunctionDef):
    """(iterable expr, lineno, kind) for every iteration point in the
    function's own body: for loops and comprehension generators."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(node, (ast.For, ast.AsyncFor)):
            yield node.iter, node.lineno, "for"
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                yield gen.iter, getattr(gen.iter, "lineno", node.lineno), \
                    "comp"
        stack.extend(ast.iter_child_nodes(node))


def run(ctx: AnalysisContext) -> list[Finding]:
    out: list[Finding] = []
    for sf in ctx.files_under(*SCOPE):
        if sf.tree is None:
            continue
        set_names = _set_names_in(sf.tree)
        parents = _parents(sf.tree)
        seen_symbols: dict[str, int] = {}

        def emit(qual, line, kind, expr_node, msg):
            try:
                text = ast.unparse(expr_node)
            except Exception:  # pragma: no cover — unparse total on exprs
                text = "?"
            base = f"{qual}:{kind} {text}"
            n = seen_symbols.get(base, 0)
            seen_symbols[base] = n + 1
            symbol = base if n == 0 else f"{base}#{n + 1}"
            out.append(Finding(
                rule="replay-determinism", path=sf.path, line=line,
                symbol=symbol, message=msg))

        for qual, fn in _functions(sf.tree):
            for it, line, _k in _iter_sites(fn):
                if _order_pinned(it, parents):
                    continue
                if _is_set_expr(it, set_names):
                    emit(qual, line, "set-iter", it,
                         "iterating a set in a serialization/hashing "
                         "path: CPython hash randomization makes the "
                         "order differ across processes, so replayed or "
                         "mirrored output diverges — wrap in sorted()")
                elif _fs_listing_call(it):
                    emit(qual, line, "fs-iter", it,
                         "iterating a directory listing unsorted in a "
                         "serialization/hashing path: listdir/glob/"
                         "iterdir order is filesystem-arbitrary and "
                         "replays differently across hosts — wrap in "
                         "sorted()")
            # float reductions + direct set consumption (a set handed
            # whole to join/list/str/...: serialized in hash order
            # without any for-loop for the iteration check to see)
            stack = list(fn.body)
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(node, ast.Call):
                    d = _dotted(node.func)
                    leaf = (node.func.attr
                            if isinstance(node.func, ast.Attribute)
                            else d)
                    if d in ("sum", "math.fsum") and node.args \
                            and _is_set_expr(node.args[0], set_names) \
                            and not _order_pinned(node, parents):
                        emit(qual, node.lineno, "float-sum",
                             node.args[0],
                             "float reduction over an unordered "
                             "container: addition order changes the "
                             "value, which survives into digests — "
                             "sum(sorted(...)) or use an ordered "
                             "container")
                    elif d not in ("sum", "math.fsum") \
                            and leaf not in _ORDER_FIXERS:
                        for a in node.args:
                            if _is_set_expr(a, set_names) \
                                    and not _order_pinned(a, parents):
                                emit(qual, a.lineno, "set-consume", a,
                                     "a set handed whole to "
                                     f"{leaf or '?'}() is consumed in "
                                     "hash-randomized order — pass "
                                     "sorted(...) instead (or suppress "
                                     "with the order-free argument)")
                stack.extend(ast.iter_child_nodes(node))
    return out
