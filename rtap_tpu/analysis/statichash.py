"""Static-argument hygiene: hashable statics, no jit churn in loops.

Two rules over the jit wrappers the kernel model discovers
(analysis/kernels.py):

``static-hash`` — a ``static_argnames``/``static_argnums`` entry must
name a real parameter and must be a hashable, frozen type. Statics are
dict keys in jax's compile cache: an unhashable static (list/dict/set/
ndarray) is a TypeError at the first call, a *mutable-but-hashable*
one is worse — a silently stale compile. The check is on the
declared annotation (``cfg: ModelConfig`` — a frozen dataclass — is
the idiom; ``cfg: dict`` is the finding) plus dangling names/indices.

``jit-churn`` — ``jax.jit(...)`` (or ``partial(jax.jit, ...)``)
evaluated inside a ``for``/``while`` body, or jit over a ``lambda``,
builds a FRESH wrapper per iteration whose cache is thrown away —
recompile churn. The AOT warm-up's cold-compile counter is the runtime
dual (service/aot.py); this is the static gate: the fix is hoisting
the wrapper to module scope or an ``lru_cache``-keyed factory (the
``_sharded_chunk_fn`` idiom, ops/step.py).
"""

from __future__ import annotations

import ast

from rtap_tpu.analysis.core import AnalysisContext, Finding
from rtap_tpu.analysis.kernels import build_kernel_model, dotted, \
    functions_in

PASS_NAME = "static-hash"
PARTITION = "file"
RULES = {
    "static-hash": "jit static arg that is unhashable/mutable by "
                   "annotation, or names no parameter",
    "jit-churn": "jax.jit constructed inside a loop (or over a "
                 "lambda) — a fresh compile cache per iteration",
}

#: annotations that cannot (or must not) be jit statics
_UNHASHABLE = frozenset({
    "list", "dict", "set", "bytearray", "List", "Dict", "Set",
    "np.ndarray", "numpy.ndarray", "jnp.ndarray", "jax.Array",
})


def _annotation_names(ann: ast.AST) -> list[str]:
    out = []
    for node in ast.walk(ann):
        if isinstance(node, (ast.Name, ast.Attribute)):
            d = dotted(node)
            if d:
                out.append(d)
        elif isinstance(node, ast.Constant) \
                and isinstance(node.value, str):
            out.append(node.value)
    return out


def _is_jit_call(node: ast.Call) -> bool:
    d = dotted(node.func)
    if d in ("jax.jit", "jit"):
        return True
    leaf = d.rsplit(".", 1)[-1] if d else None
    return leaf == "partial" and bool(node.args) \
        and dotted(node.args[0]) in ("jax.jit", "jit")


def run(ctx: AnalysisContext) -> list[Finding]:
    model = build_kernel_model(ctx)
    out: list[Finding] = []

    # ---- static args must be declared params with frozen types ------
    for w in model.wrappers:
        params = w.params + w.kwonly
        by_name = {a.arg: a for a in
                   w.node.args.args + w.node.args.kwonlyargs}
        for name in sorted(w.static_argnames):
            if name not in params:
                out.append(Finding(
                    rule="static-hash", path=w.path, line=w.line,
                    symbol=f"{w.name}:static:{name}",
                    message=f"static_argnames names '{name}' but "
                            f"{w.name}() has no such parameter — a "
                            "rename left the static spec behind "
                            "(jax raises only when it is USED)"))
                continue
            ann = by_name[name].annotation
            if ann is not None and any(
                    a in _UNHASHABLE or a.split("[")[0] in _UNHASHABLE
                    for a in _annotation_names(ann)):
                out.append(Finding(
                    rule="static-hash", path=w.path, line=w.line,
                    symbol=f"{w.name}:static:{name}",
                    message=f"static arg '{name}' is annotated with "
                            "an unhashable/mutable type — statics are "
                            "compile-cache keys; use a frozen "
                            "dataclass or tuple"))
        for i in sorted(w.static_argnums | w.donate_argnums):
            if not (0 <= i < len(w.params)):
                which = "static_argnums" if i in w.static_argnums \
                    else "donate_argnums"
                out.append(Finding(
                    rule="static-hash", path=w.path, line=w.line,
                    symbol=f"{w.name}:argnum:{i}",
                    message=f"{which} index {i} is out of range for "
                            f"{w.name}()'s {len(w.params)} positional "
                            "params — a signature edit left the spec "
                            "behind"))

    # ---- jit churn: jit built in loops / over lambdas ---------------
    for sf in ctx.files:
        # textual prefilter: the walk below visits every node of every
        # function — skip the many files that never say "jit" at all
        if sf.tree is None or "jit" not in sf.text:
            continue
        for qual, fn in functions_in(sf.tree):
            loop_depth_nodes = []

            def walk(node, in_loop):
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        continue
                    child_in_loop = in_loop or isinstance(
                        node, (ast.For, ast.While)) and child in (
                            getattr(node, "body", []))
                    if isinstance(child, ast.Call) \
                            and _is_jit_call(child):
                        if child_in_loop:
                            loop_depth_nodes.append((child, "loop"))
                        elif any(isinstance(a, ast.Lambda)
                                 for a in child.args):
                            loop_depth_nodes.append((child, "lambda"))
                    walk(child, child_in_loop)

            walk(fn, False)
            for call, kind in loop_depth_nodes:
                if kind == "loop":
                    msg = ("jax.jit evaluated inside a loop — a fresh "
                           "wrapper (and compile cache) per iteration; "
                           "hoist it to module scope or key it through "
                           "an lru_cache factory (the _sharded_chunk_fn "
                           "idiom)")
                else:
                    msg = ("jax.jit over a lambda — the wrapper cannot "
                           "be cache-shared across call sites; def a "
                           "named function")
                out.append(Finding(
                    rule="jit-churn", path=sf.path, line=call.lineno,
                    symbol=f"{qual}:jit-{kind}",
                    message=msg))
    return out
