"""Scaling math: SCALING.md's bytes/stream numbers, re-derived statically.

Rule ``scaling-math`` (ISSUE 15) — the flag-docs/metric-catalog gate's
memory twin. SCALING.md's analytic table (bytes/stream per permanence
domain, max streams/chip, largest tensors) is the number every capacity
decision on ROADMAP-3's 50k→100k ladder stands on, and it is generated
by running ``state_nbytes`` — so a config edit (pool sizes, encoder
width) silently stales the committed doc until someone reruns
``scripts/scaling_law.py``. This pass re-derives the same numbers from
PURE AST:

* geometry from ``cluster_preset``'s literal arguments in config.py
  (dataclass defaults fill unspecified fields);
* the per-leaf byte formulas of the models/state.py layout (the same
  shapes the partition contract covers);
* quantized-grid byte widths from models/perm.py's dtype table — the
  v3 dtype-domain pass's ground truth, so the two rails can't disagree;
* the HBM budget constants from scripts/scaling_law.py.

and cross-checks every quoted figure. A mismatch means the doc is stale
(or the derivation wrong — either way a human must look): finding
symbols ``bytes:<domain>``, ``fit:<domain>``, ``tensor:<name>``,
``derive:<what>`` (inputs present but underivable).
"""

from __future__ import annotations

import ast
import os
import re

from rtap_tpu.analysis.core import AnalysisContext, Finding

PASS_NAME = "scaling-math"
PARTITION = "program"
RULES = {
    "scaling-math": "SCALING.md bytes/stream, streams/chip, and "
                    "largest-tensor figures cross-checked against a "
                    "static derivation from the config dataclasses",
}

_CONFIG = "rtap_tpu/config.py"
_PERM = "rtap_tpu/models/perm.py"
_LAW = "scripts/scaling_law.py"

#: SCALING.md analytic-table row: | <domain> | <bytes> | <fit> |
_ROW_RE = re.compile(
    r"^\|\s*(f32|u16 quanta|u8 quanta)\s*\|\s*([\d,]+)\s*\|"
    r"\s*([\d,]+)\s*\|")
_TENSOR_LINE_RE = re.compile(r"^Largest tensors \(u16 domain\):(.*)$")
_TENSOR_RE = re.compile(r"`?(\w+)`?\s+([\d,]+)\s*B")

_DOMAIN_BITS = {"f32": 0, "u16 quanta": 16, "u8 quanta": 8}
_DTYPE_BYTES = {"float32": 4, "uint16": 2, "uint8": 1}


def _const_eval(node: ast.AST):
    """Evaluate a numeric constant expression (16 * 1024**3 style)."""
    if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)):
        return node.value
    if isinstance(node, ast.BinOp):
        left, right = _const_eval(node.left), _const_eval(node.right)
        if left is None or right is None:
            return None
        ops = {ast.Add: lambda a, b: a + b, ast.Sub: lambda a, b: a - b,
               ast.Mult: lambda a, b: a * b, ast.Pow: lambda a, b: a ** b,
               ast.FloorDiv: lambda a, b: a // b,
               ast.Div: lambda a, b: a / b}
        fn = ops.get(type(node.op))
        return fn(left, right) if fn else None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_eval(node.operand)
        return -inner if inner is not None else None
    return None


def _dataclass_defaults(tree: ast.AST, cls: str) -> dict:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            out = {}
            for st in node.body:
                if isinstance(st, ast.AnnAssign) and st.value is not None \
                        and isinstance(st.target, ast.Name):
                    v = _const_eval(st.value)
                    if v is not None:
                        out[st.target.id] = v
            return out
    return {}


def _preset_kwargs(tree: ast.AST, sub: str) -> dict | None:
    """Literal keyword args of the ``<sub>Config(...)`` call inside
    ``cluster_preset``'s returned ModelConfig (None: not found)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) \
                and node.name == "cluster_preset":
            for call in ast.walk(node):
                if isinstance(call, ast.Call) \
                        and isinstance(call.func, ast.Name) \
                        and call.func.id == sub:
                    out = {}
                    for kw in call.keywords:
                        v = _const_eval(kw.value)
                        if kw.arg is not None and v is not None:
                            out[kw.arg] = v
                    return out
    return None


def _perm_bytes_table(perm_sf) -> dict[int, int] | None:
    """bits -> storage bytes, read from models/perm.py's dtype dict
    (``{0: np.float32, 8: np.uint8, 16: np.uint16}``) — the same table
    the v3 dtype-domain declarations quantize onto."""
    if perm_sf is None or perm_sf.tree is None:
        return None
    for node in ast.walk(perm_sf.tree):
        if not isinstance(node, ast.Dict) or len(node.keys) < 3:
            continue
        out = {}
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, int)):
                break
            name = None
            if isinstance(v, ast.Attribute):
                name = v.attr
            if name not in _DTYPE_BYTES:
                break
            out[k.value] = _DTYPE_BYTES[name]
        else:
            if {0, 8, 16} <= set(out):
                return out
    return None


def _law_constants(law_sf) -> tuple[float, float] | None:
    if law_sf is None or law_sf.tree is None:
        return None
    hbm = reserve = None
    for node in ast.walk(law_sf.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            if node.targets[0].id == "HBM_BYTES":
                hbm = _const_eval(node.value)
            elif node.targets[0].id == "WORKSPACE_RESERVE":
                reserve = _const_eval(node.value)
    if hbm is None or reserve is None:
        return None
    return hbm, reserve


def derive_leaf_bytes(cfg_sf, perm_sf, bits: int) -> dict[str, int] | None:
    """Per-leaf byte sizes of one cluster-preset stream at permanence
    domain `bits` — the models/state.py layout, derived statically."""
    if cfg_sf is None or cfg_sf.tree is None:
        return None
    tree = cfg_sf.tree
    perm_b = _perm_bytes_table(perm_sf)
    if perm_b is None or bits not in perm_b:
        return None
    sp = _preset_kwargs(tree, "SPConfig")
    tm = _preset_kwargs(tree, "TMConfig")
    rdse = _preset_kwargs(tree, "RDSEConfig")
    date = _preset_kwargs(tree, "DateConfig")
    if sp is None or tm is None or rdse is None:
        return None
    sp = {**_dataclass_defaults(tree, "SPConfig"), **sp}
    tm = {**_dataclass_defaults(tree, "TMConfig"), **tm}
    rdse = {**_dataclass_defaults(tree, "RDSEConfig"), **rdse}
    date = {**_dataclass_defaults(tree, "DateConfig"), **(date or {})}
    try:
        C = int(sp["columns"])
        K = int(tm["cells_per_column"])
        S = int(tm["max_segments_per_cell"])
        M = int(tm["max_synapses_per_segment"])
        rdse_size = int(rdse["size"])
        date_size = (int(date["time_of_day_size"])
                     if date.get("time_of_day_width") else 0) \
            + int(date.get("weekend_width", 0))
    except (KeyError, TypeError, ValueError):
        return None
    n_fields = 1   # cluster_preset leaves ModelConfig.n_fields default
    nin = rdse_size * n_fields + date_size
    cells, segs, pool = C * K, C * K * S, C * K * S * M
    presyn_b = 2 if cells <= (1 << 15) - 1 else 4
    pb = perm_b[bits]
    if bool(sp.get("sparse_pool", False)):
        # member-index layout (ISSUE 18): members i16/i32 [C, P] + perm
        # [C, P] replace the dense potential/perm plane; P mirrors
        # ModelConfig.sp_members (pool_members pin wins, else the
        # round-half-up potential fraction) and the index dtype mirrors
        # models/state.py members_dtype
        P = int(sp.get("pool_members", 0) or 0) or int(float(sp["potential_pct"]) * nin + 0.5)
        members_b = 2 if nin <= (1 << 15) - 1 else 4
        sp_leaves = {"members": C * P * members_b, "perm": C * P * pb}
    else:
        sp_leaves = {"potential": C * nin, "perm": C * nin * pb}
    return {
        **sp_leaves,
        "boost": C * 4, "overlap_duty": C * 4, "active_duty": C * 4,
        "sp_iter": 4,
        "presyn": pool * presyn_b, "syn_perm": pool * pb,
        "seg_last": segs * 4, "active_seg": segs, "matching_seg": segs,
        "seg_pot": segs * 2, "prev_active": cells, "prev_winner": cells,
        "tm_iter": 4, "tm_overflow": 4,
        "enc_offset": n_fields * 4, "enc_bound": n_fields,
        "enc_resolution": n_fields * 4,
    }


def derived_stream_bytes(root: str, bits: int) -> int | None:
    """Analyzer-derived bytes/stream of one cluster-preset stream, read
    from the REAL repo files under `root` (None when underivable). This is
    the same static derivation the SCALING.md gate runs; bench.py gates
    its honest ``state_nbytes`` figure against it so a layout change that
    moves real bytes without moving the doc twin fails loudly instead of
    drifting (ISSUE 18 satellite 5)."""
    from rtap_tpu.analysis.core import SourceFile

    sfs = []
    for rel in (_CONFIG, _PERM):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as fh:
                sfs.append(SourceFile(rel, fh.read()))
        except OSError:
            return None
    leaves = derive_leaf_bytes(sfs[0], sfs[1], bits)
    return None if leaves is None else sum(leaves.values())


def run(ctx: AnalysisContext) -> list[Finding]:
    text = ctx.scaling()
    if not text:
        return []
    lines = text.splitlines()
    rows: list[tuple[str, int, int, int]] = []   # domain, bytes, fit, ln
    tensor_line: tuple[str, int] | None = None
    for i, line in enumerate(lines, start=1):
        m = _ROW_RE.match(line.strip())
        if m:
            rows.append((m.group(1), int(m.group(2).replace(",", "")),
                         int(m.group(3).replace(",", "")), i))
        m = _TENSOR_LINE_RE.match(line.strip())
        if m:
            tensor_line = (m.group(1), i)
    if not rows and tensor_line is None:
        return []   # no analytic table to check (fixture contexts)

    out: list[Finding] = []
    cfg_sf = ctx.file(_CONFIG)
    perm_sf = ctx.file(_PERM)
    law = _law_constants(ctx.file(_LAW))
    per_domain = {bits: derive_leaf_bytes(cfg_sf, perm_sf, bits)
                  for bits in (0, 16, 8)}
    if any(v is None for v in per_domain.values()):
        out.append(Finding(
            rule="scaling-math", path="SCALING.md", line=1,
            symbol="derive:inputs",
            message="SCALING.md quotes an analytic bytes/stream table "
                    "but the cluster-preset geometry could not be "
                    "derived from rtap_tpu/config.py + models/perm.py "
                    "— the doc's memory twin is blind; restore the "
                    "literal preset/dtype tables"))
        return out

    for domain, quoted_bytes, quoted_fit, ln in rows:
        bits = _DOMAIN_BITS[domain]
        derived = sum(per_domain[bits].values())
        if derived != quoted_bytes:
            out.append(Finding(
                rule="scaling-math", path="SCALING.md", line=ln,
                symbol=f"bytes:{domain.split()[0]}",
                message=f"quoted {quoted_bytes:,} bytes/stream for "
                        f"{domain} but the config derives "
                        f"{derived:,} — the table is stale; rerun "
                        "scripts/scaling_law.py"))
        elif law is not None:
            hbm, reserve = law
            fit = int((hbm - reserve) // derived)
            if fit != quoted_fit:
                out.append(Finding(
                    rule="scaling-math", path="SCALING.md", line=ln,
                    symbol=f"fit:{domain.split()[0]}",
                    message=f"quoted {quoted_fit:,} streams/chip for "
                            f"{domain} but (HBM - reserve) // "
                            f"bytes = {fit:,} — the capacity column "
                            "is stale"))

    if tensor_line is not None:
        rest, ln = tensor_line
        u16 = per_domain[16]
        for name, num in _TENSOR_RE.findall(rest):
            quoted = int(num.replace(",", ""))
            if name in u16 and u16[name] != quoted:
                out.append(Finding(
                    rule="scaling-math", path="SCALING.md", line=ln,
                    symbol=f"tensor:{name}",
                    message=f"largest-tensor line quotes {name} at "
                            f"{quoted:,} B but the config derives "
                            f"{u16[name]:,} B"))
            elif name not in u16:
                out.append(Finding(
                    rule="scaling-math", path="SCALING.md", line=ln,
                    symbol=f"tensor:{name}",
                    message=f"largest-tensor line names {name!r} which "
                            "the derived state layout does not "
                            "contain — a renamed leaf left the doc "
                            "behind"))
    return out
