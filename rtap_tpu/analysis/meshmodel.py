"""The mesh model the v4 (mesh-readiness) passes share (ISSUE 15).

ROADMAP-1 (pod-scale sharded serving: 1M streams on a v5e-8 mesh) is
blocked not by the kernels — ``sharded_chunk_step`` is collective-free
and bit-exact under the mesh — but by the serve stack's implicit
single-device assumptions: ``jax.local_devices()[0]`` reads, blanket
``device_get`` fetches, journal/lease/alert paths with no shard
qualifier. SDR theory (PAPERS.md 1503.07469) makes stream-axis sharding
embarrassingly parallel — per-stream state never couples across the
mesh — so every cross-shard data or resource flow is a bug-in-waiting,
and all of them are statically visible. This module builds the one
model the four mesh passes share, once per run, memoized on the
context:

* **mesh entry points** — functions whose own body calls the
  ``rtap_tpu/parallel`` placement API (``make_stream_mesh`` /
  ``stream_sharding`` / ``put_sharded`` / ``shard_state`` /
  ``broadcast_group_state`` / ``init_distributed``), every function in
  ``rtap_tpu/parallel/`` itself, plus explicit declarations::

      # rtap: mesh-entry — registry builds the group mesh here

  Entry points are where collectives and device placement legitimately
  live; everywhere else they are findings.

* **host boundaries** — functions declared as the place where sharded
  device values legitimately materialize on host::

      # rtap: host-boundary — checkpoint save fetches the full tree

  (on the ``def``/decorator line or the contiguous comment block above,
  the ``twin[...]`` placement grammar). Mesh entry points are host
  boundaries too — they own placement in both directions.

* **partition tables + state-tree constructors** — the declared
  partition rule for every state leaf built in ``rtap_tpu/models/``.
  Rules (docs/ANALYSIS.md)::

      # rtap: partition[presyn=shard-streams, scores=host-only]  (module)
      "boost": np.ones(C, np.float32),  # rtap: partition[shard-streams]

  Valid rules: ``shard-streams`` (leading G axis splits over the
  mesh), ``replicated`` (every shard holds the full leaf), and
  ``host-only`` (never device-resident; per-shard process state).
  Constructors are discovered structurally: any models/ function whose
  body builds dict literals of numpy/jnp arrays under string keys (the
  state.py/likelihood.py idiom) — so a brand-new state tree can never
  dodge the contract by not opting in.

* **shard resources** — filesystem-path-producing sites in the serve
  stack (``TickJournal``/``Lease``/``AlertWriter`` construction, alert
  sidecar suffixes, checkpoint group-claim components): the
  shard-resource pass's ground truth for the "one shard-qualified
  helper" rule (service/shardpath.py).

Everything is pure AST — no jax import, same discipline as the rest of
the analyzer.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from rtap_tpu.analysis.core import AnalysisContext, Finding, SourceFile
from rtap_tpu.analysis.kernels import dotted, functions_in, own_body_nodes

__all__ = [
    "MESH_APIS",
    "MODULE_QUAL",
    "MeshModel",
    "PARTITION_RULES",
    "ResourceSite",
    "StateConstructor",
    "build_mesh_model",
    "fn_marker",
    "functions_of",
    "module_level_nodes",
    "scopes_of",
]


def functions_of(sf: SourceFile) -> list:
    """``functions_in(sf.tree)``, memoized on the SourceFile — the v4
    passes each iterate every function of every scoped file, and four
    independent full-tree walks per file blew the warm-run budget."""
    cached = getattr(sf, "_functions", None)
    if cached is None:
        cached = functions_in(sf.tree) if sf.tree is not None else []
        sf._functions = cached
    return cached


#: the synthetic qualname for import-time code — module body and class
#: bodies outside any def. The mesh passes must see it too: a
#: module-level ``devices()[0]`` pick or ``path + ".corr"`` mint runs
#: at import and is MORE dangerous than the same line in a function
MODULE_QUAL = "(module)"


def module_level_nodes(sf: SourceFile):
    """Every AST node that executes at import time: the module body and
    class bodies, excluding function defs (those get their own
    qualnames from :func:`functions_of`)."""
    stack = list(sf.tree.body) if sf.tree is not None else []
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def scopes_of(sf: SourceFile):
    """(qualname, node iterable) for every scope a mesh pass must scan:
    the import-time scope first, then each function body."""
    yield MODULE_QUAL, module_level_nodes(sf)
    for qual, fn in functions_of(sf):
        yield qual, own_body_nodes(fn)


#: the parallel-placement API: calling any of these makes the caller a
#: mesh entry point (it is MAKING a placement decision)
MESH_APIS = frozenset({
    "make_stream_mesh", "stream_sharding", "put_sharded", "shard_state",
    "broadcast_group_state", "init_distributed",
})

#: valid partition rules (docs/ANALYSIS.md)
PARTITION_RULES = ("shard-streams", "replicated", "host-only")

#: alert sidecar suffixes — the names a second shard would clobber if
#: minted by bare concat (service/shardpath.py owns them now)
RESOURCE_SUFFIXES = (".corr", ".epoch")

_MESH_ENTRY_RE = re.compile(r"#\s*rtap:\s*mesh-entry\b")
_HOST_BOUNDARY_RE = re.compile(r"#\s*rtap:\s*host-boundary\b")
_PARTITION_MODULE_RE = re.compile(
    r"#\s*rtap:\s*partition\[([A-Za-z_][\w]*\s*=\s*[\w-]+"
    r"(?:\s*,\s*[A-Za-z_][\w]*\s*=\s*[\w-]+)*)\]")
_PARTITION_TRAILING_RE = re.compile(r"#\s*rtap:\s*partition\[([\w-]+)\]")


def fn_marker(sf: SourceFile, fn: ast.FunctionDef, marker: re.Pattern) -> bool:
    """True when `marker` appears on the ``def`` line, a decorator
    line, or the contiguous comment block directly above them — the
    same placement grammar as ``# rtap: twin[...]`` (kernels.py)."""
    first = min([fn.lineno] + [d.lineno for d in fn.decorator_list])
    for ln in range(first, fn.lineno + 1):
        if ln - 1 < len(sf.lines) and marker.search(sf.lines[ln - 1]):
            return True
    ln = first - 1
    while ln >= 1 and sf.lines[ln - 1].lstrip().startswith("#"):
        if marker.search(sf.lines[ln - 1]):
            return True
        ln -= 1
    return False


@dataclass
class StateConstructor:
    """One discovered state-tree-building function in models/."""

    qual: str
    path: str
    line: int
    #: (leaf name, line of the dict key) in source order
    leaves: list[tuple[str, int]] = field(default_factory=list)


@dataclass
class ResourceSite:
    """One filesystem-resource construction site in the serve stack."""

    kind: str        # "TickJournal" | "Lease" | "AlertWriter" | "mint"
    path: str
    line: int
    qual: str
    #: for constructor sites: the path argument node; for mints: the
    #: offending expression
    node: ast.AST | None = None
    detail: str = ""


@dataclass
class MeshModel:
    #: (path, qualname) of every mesh entry point
    entry_points: set[tuple[str, str]] = field(default_factory=set)
    #: (path, qualname) of every declared host boundary (entry points
    #: are host boundaries too — see module docstring)
    host_boundaries: set[tuple[str, str]] = field(default_factory=set)
    #: models/ partition tables: path -> {leaf name -> (rule, line)}
    partition_tables: dict[str, dict[str, tuple[str, int]]] = \
        field(default_factory=dict)
    #: models/ trailing annotations: path -> {line -> rule}
    partition_trailing: dict[str, dict[int, str]] = field(default_factory=dict)
    #: malformed partition annotations (unknown rule tokens)
    partition_errors: list[Finding] = field(default_factory=list)
    #: discovered state-tree constructors in models/
    constructors: list[StateConstructor] = field(default_factory=list)
    #: merged leaf -> rule across every models/ file (consumer checks);
    #: None when the leaf has no (valid) declaration yet
    leaf_rules: dict[str, str | None] = field(default_factory=dict)
    #: serve-stack resource construction sites (shard-resource pass)
    resources: list[ResourceSite] = field(default_factory=list)

    def is_entry(self, path: str, qual: str) -> bool:
        if path.startswith("rtap_tpu/parallel/"):
            return True
        return _self_or_outer(self.entry_points, path, qual)

    def is_host_boundary(self, path: str, qual: str) -> bool:
        return self.is_entry(path, qual) or _self_or_outer(
            self.host_boundaries, path, qual)

    def rule_of(self, leaf: str) -> str | None:
        return self.leaf_rules.get(leaf)


def _self_or_outer(table: set, path: str, qual: str) -> bool:
    """A nested function inherits its enclosing function's declaration
    (the annotation sits on the outer ``def``; locals are its body)."""
    parts = qual.split(".")
    for i in range(len(parts), 0, -1):
        if (path, ".".join(parts[:i])) in table:
            return True
    return False


# ------------------------------------------------------- partition tables --

def partition_annotations(sf: SourceFile) -> tuple[dict[str, tuple[str, int]],
                                                   dict[int, str],
                                                   list[Finding]]:
    """(module-wide leaf->(rule, line), line->rule trailing form, syntax
    findings) — the dtype-domain table grammar, reused for partitions."""
    table: dict[str, tuple[str, int]] = {}
    trailing: dict[int, str] = {}
    bad: list[Finding] = []
    for i, line in enumerate(sf.lines, start=1):
        m = _PARTITION_MODULE_RE.search(line)
        if m:
            for pair in m.group(1).split(","):
                name, rule = (s.strip() for s in pair.split("="))
                if rule not in PARTITION_RULES:
                    bad.append(Finding(
                        rule="partition-contract", path=sf.path, line=i,
                        symbol=f"partition-syntax:{name}",
                        message=f"unknown partition rule '{rule}' — "
                                f"valid: {', '.join(PARTITION_RULES)}"))
                else:
                    table[name] = (rule, i)
            continue
        m = _PARTITION_TRAILING_RE.search(line)
        if m:
            rule = m.group(1)
            if rule not in PARTITION_RULES:
                bad.append(Finding(
                    rule="partition-contract", path=sf.path, line=i,
                    symbol="partition-syntax:trailing",
                    message=f"unknown partition rule '{rule}' — valid: "
                            f"{', '.join(PARTITION_RULES)}"))
            else:
                trailing[i] = rule
    return table, trailing, bad


def _np_rooted_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            d = dotted(sub.func)
            if d and d.split(".", 1)[0] in ("np", "numpy", "jnp"):
                return True
    return False


def _constructor_leaves(fn: ast.FunctionDef) -> list[tuple[str, int]]:
    """String keys of every array-building dict literal in `fn` (the
    state-tree idiom: string keys over np/jnp constructor values).
    Returns [] when the function does not look like a constructor
    (fewer than 3 such keys across all its dicts)."""
    leaves: list[tuple[str, int]] = []
    for node in own_body_nodes(fn):
        if not isinstance(node, ast.Dict):
            continue
        if not any(v is not None and _np_rooted_call(v)
                   for v in node.values):
            continue
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                leaves.append((k.value, k.lineno))
    return leaves if len(leaves) >= 3 else []


# ----------------------------------------------------- resource registry --

#: serve-stack classes whose first argument is a filesystem path a
#: second shard would clobber (the shard-resource constructor registry)
_RESOURCE_CLASSES = ("TickJournal", "Lease", "AlertWriter")

#: files the resource registry scans (the serve stack's path-producing
#: surface; ops/models build no files)
RESOURCE_SCOPE = ("rtap_tpu/service/", "rtap_tpu/resilience/",
                  "rtap_tpu/correlate/", "rtap_tpu/obs/",
                  "rtap_tpu/__main__.py")


def _is_group_claim_fstring(node: ast.JoinedStr) -> bool:
    """f"group{gi:04d}" — the checkpoint group-claim component. The
    zero-padded spec is what distinguishes an on-disk claim name from
    the many diagnostic f-strings that merely SAY "group" (trace track
    names, chaos messages, stats keys)."""
    has_claim_spec = any(
        isinstance(v, ast.FormattedValue)
        and isinstance(v.format_spec, ast.JoinedStr)
        and any(isinstance(s, ast.Constant) and "04d" in str(s.value)
                for s in v.format_spec.values)
        for v in node.values)
    return has_claim_spec and any(
        isinstance(v, ast.Constant) and isinstance(v.value, str)
        and v.value.endswith("group") for v in node.values)


def _mint_detail(node: ast.AST) -> str | None:
    """Non-None when `node` mints a shard-scoped resource path by bare
    string construction — the exact thing service/shardpath.py exists
    to own. Returns the human label of what was minted."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        for side in (node.left, node.right):
            if isinstance(side, ast.Constant) and isinstance(side.value, str):
                if side.value in RESOURCE_SUFFIXES:
                    return f"sidecar suffix {side.value!r}"
    if isinstance(node, ast.JoinedStr):
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                for suf in RESOURCE_SUFFIXES:
                    if suf in v.value:
                        return f"sidecar suffix {suf!r}"
        if _is_group_claim_fstring(node):
            return "checkpoint group-claim component"
    if isinstance(node, ast.Call):
        d = dotted(node.func)
        leaf = d.rsplit(".", 1)[-1] if d else None
        if leaf in ("join", "with_name", "with_suffix"):
            for a in node.args:
                if isinstance(a, ast.Constant) and isinstance(a.value, str) \
                        and (a.value in RESOURCE_SUFFIXES
                             or a.value.startswith("group")):
                    return f"resource component {a.value!r}"
                if isinstance(a, ast.JoinedStr) \
                        and _is_group_claim_fstring(a):
                    return "checkpoint group-claim component"
    return None


def build_mesh_model(ctx: AnalysisContext) -> MeshModel:
    """Build (or return the memoized) mesh model for this context."""
    cached = getattr(ctx, "_mesh_model", None)
    if cached is not None:
        return cached
    model = MeshModel()

    for sf in ctx.files:
        if sf.tree is None:
            continue
        in_models = sf.path.startswith("rtap_tpu/models/")
        if in_models:
            table, trailing, bad = partition_annotations(sf)
            if table:
                model.partition_tables[sf.path] = table
            if trailing:
                model.partition_trailing[sf.path] = trailing
            model.partition_errors.extend(bad)
        # text prefilters: most files never mention the placement API or
        # the annotations, and a full body walk per function across the
        # whole surface is what blows the warm-run budget
        may_entry_ann = "mesh-entry" in sf.text
        may_hb_ann = "host-boundary" in sf.text
        may_call_api = any(api in sf.text for api in MESH_APIS)
        if not (may_entry_ann or may_hb_ann or may_call_api or in_models):
            continue
        for qual, fn in functions_of(sf):
            # ---- entry points / host boundaries ---------------------
            if may_entry_ann and fn_marker(sf, fn, _MESH_ENTRY_RE):
                model.entry_points.add((sf.path, qual))
            elif may_call_api:
                for node in own_body_nodes(fn):
                    if isinstance(node, ast.Call):
                        d = dotted(node.func)
                        if d and d.rsplit(".", 1)[-1] in MESH_APIS:
                            model.entry_points.add((sf.path, qual))
                            break
            if may_hb_ann and fn_marker(sf, fn, _HOST_BOUNDARY_RE):
                model.host_boundaries.add((sf.path, qual))
            # ---- state-tree constructors ----------------------------
            if in_models:
                leaves = _constructor_leaves(fn)
                if leaves:
                    model.constructors.append(StateConstructor(
                        qual=qual, path=sf.path, line=fn.lineno,
                        leaves=leaves))

    # merged leaf -> rule view for the consumer checks: trailing form
    # wins over the module table (it sits on the leaf itself). Two
    # files declaring DIFFERENT rules for one leaf name is a finding,
    # not a first-wins tiebreak — the consumer checks would otherwise
    # validate against whichever file enumerates first
    origin: dict[str, tuple[str, str]] = {}   # leaf -> (rule, path)
    for c in model.constructors:
        table = model.partition_tables.get(c.path, {})
        trailing = model.partition_trailing.get(c.path, {})
        for name, line in c.leaves:
            rule = trailing.get(line) or table.get(name, (None, 0))[0]
            prev = origin.get(name)
            if rule is not None and prev is not None \
                    and prev[0] is not None and prev[0] != rule:
                model.partition_errors.append(Finding(
                    rule="partition-contract", path=c.path, line=line,
                    symbol=f"partition-conflict:{name}",
                    message=f"leaf {name!r} declares rule '{rule}' here "
                            f"but '{prev[0]}' in {prev[1]} — one leaf "
                            "name, one placement; rename the leaf or "
                            "reconcile the rules"))
            if prev is None or prev[0] is None:
                origin[name] = (rule, c.path)
            model.leaf_rules[name] = origin[name][0]

    # ---- shard-resource registry ------------------------------------
    for sf in ctx.files_under(*RESOURCE_SCOPE):
        if sf.tree is None:
            continue
        t = sf.text
        if not (any(s in t for s in RESOURCE_SUFFIXES)
                or ("group" in t and "04d" in t)
                or any(c in t for c in _RESOURCE_CLASSES)):
            continue   # nothing resource-shaped to register
        for qual, nodes in scopes_of(sf):
            for node in nodes:
                if isinstance(node, ast.Call):
                    d = dotted(node.func)
                    leaf = d.rsplit(".", 1)[-1] if d else None
                    if leaf in _RESOURCE_CLASSES and node.args:
                        model.resources.append(ResourceSite(
                            kind=leaf, path=sf.path, line=node.lineno,
                            qual=qual, node=node.args[0]))
                detail = _mint_detail(node)
                if detail is not None and not any(
                        r.kind == "mint" and r.path == sf.path
                        and r.line == node.lineno
                        for r in model.resources):
                    # one finding per line: an os.path.join over an
                    # f"group{gi:04d}" literal is ONE mint, not two
                    model.resources.append(ResourceSite(
                        kind="mint", path=sf.path, line=node.lineno,
                        qual=qual, node=node, detail=detail))

    ctx._mesh_model = model
    return model
