"""Child-process supervision for serve: crash, restart, catch up.

The journal + checkpoint stack (resilience/journal.py,
service/checkpoint.py) makes a serve process RESUMABLE after a hard
death; this module makes it RESUMED without a human: ``serve
--supervise`` runs the real serve loop in a child process and the
:class:`Supervisor` restarts it after every abnormal death with
exponential backoff and a restart budget. Each death is recorded as a
structured event on the incident stream (the alert JSONL file — the
same file the child writes, append-mode line writes are atomic enough
for the story to interleave correctly) and, when a postmortem dir is
armed, as a death-marker JSON next to the child's own flight-recorder
bundles (SIGKILL leaves no in-process black box; the marker + journal
ARE the black box).

Exit semantics: the child completing with rc 0 ends supervision with 0;
exhausting the restart budget exits 3 (the deaths are in the event
stream); a SIGTERM/SIGINT to the supervisor forwards to the child,
waits, and exits with the child's code. ``scripts/crash_soak.py`` drives
this class with a seeded SIGKILL schedule and verifies the resumed
run's final state and alert stream are bit-identical to a fault-free
run — the durability acceptance bar (docs/RESILIENCE.md).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import threading
import time

from rtap_tpu.obs import get_registry

__all__ = ["Supervisor", "strip_supervise_flags"]

#: serve CLI flags the supervisor consumes itself (value count follows);
#: strip_supervise_flags removes them when building the child argv
SUPERVISE_FLAGS = {
    "--supervise": 0,
    "--supervise-restarts": 1,
    "--supervise-backoff": 1,
}

#: exit code when the restart budget is exhausted
BUDGET_EXHAUSTED_RC = 3


def strip_supervise_flags(argv: list[str]) -> list[str]:
    """The child serve argv: the supervisor's own flags removed, every
    other flag passed through verbatim (both ``--flag value`` and
    ``--flag=value`` forms)."""
    out: list[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        name = arg.split("=", 1)[0]
        if name in SUPERVISE_FLAGS:
            i += 1
            if "=" not in arg:
                i += SUPERVISE_FLAGS[name]
            continue
        out.append(arg)
        i += 1
    return out


class Supervisor:
    """Run `cmd` as a child process; restart on abnormal death.

    - ``restart_budget``: maximum abnormal deaths tolerated; one more
      exits :data:`BUDGET_EXHAUSTED_RC`.
    - backoff: ``backoff_base_s * 2**(consecutive_fast_deaths - 1)``
      capped at ``backoff_max_s``; a child that stayed up at least
      ``healthy_after_s`` resets the exponent (a long-lived serve that
      finally dies deserves a fast restart, a crash loop does not).
    - ``event_path``: JSONL file for supervisor events (pass the serve
      run's ``--alerts`` file so deaths interleave with the incident
      stream); ``postmortem_dir``: death-marker JSONs land here.
    - ``log``: optional callable(str) for operator feedback (the CLI
      passes a stderr printer; this module itself never prints).
    """

    def __init__(self, cmd: list[str], restart_budget: int = 10,
                 backoff_base_s: float = 0.5, backoff_max_s: float = 30.0,
                 healthy_after_s: float = 60.0, event_path: str | None = None,
                 postmortem_dir: str | None = None, env: dict | None = None,
                 log=None):
        if restart_budget < 0:
            raise ValueError(
                f"restart_budget must be >= 0; got {restart_budget}")
        if backoff_base_s <= 0 or backoff_max_s < backoff_base_s:
            raise ValueError(
                "need 0 < backoff_base_s <= backoff_max_s; got "
                f"{backoff_base_s}, {backoff_max_s}")
        self.cmd = list(cmd)
        self.restart_budget = int(restart_budget)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.healthy_after_s = float(healthy_after_s)
        self.event_path = event_path
        self.postmortem_dir = postmortem_dir
        self.env = env
        self._log = log or (lambda msg: None)
        self.child: subprocess.Popen | None = None
        self.deaths = 0
        self.death_rcs: list[int] = []  # raw rc per abnormal death
        self.kill_signals: list[int] = []  # signal number (0 = exited)
        self._stop = threading.Event()
        self._obs_restarts = get_registry().counter(
            "rtap_obs_supervisor_restarts_total",
            "serve child processes restarted after an abnormal death")
        # ISSUE 6 satellite: the dashboard-facing restart counter. Lives
        # in the PARENT (which survives every child death), cumulative
        # over the supervision run — joined with the child-side
        # rtap_obs_run_epoch gauge it lets dashboards tell a restart's
        # counter reset from a rollover.
        self._obs_restarts_cum = get_registry().counter(
            "rtap_obs_restarts_total",
            "cumulative serve child restarts over this supervision run "
            "(parent-process registry; pairs with the child's "
            "rtap_obs_run_epoch gauge)")

    # ---- event plumbing ---------------------------------------------
    def _event(self, event: dict) -> None:
        """Best-effort structured event: one JSONL line, appended +
        flushed (the incident stream must tell the restart story even
        if nothing else survived the death)."""
        line = json.dumps({"event": event["event"], **event,
                           "supervisor_pid": os.getpid()})
        self._log(f"supervisor: {line}")
        if not self.event_path:
            return
        try:
            # heal a torn tail first: the child was very possibly killed
            # mid-write, and appending straight after its partial line
            # would merge THIS event into one unparseable fragment
            from rtap_tpu.service.alerts import heal_torn_tail

            heal_torn_tail(self.event_path)
            with open(self.event_path, "a") as f:
                f.write(line + "\n")
                f.flush()
        except OSError:
            pass

    def _death_marker(self, rc: int, uptime_s: float) -> None:
        if not self.postmortem_dir:
            return
        try:
            os.makedirs(self.postmortem_dir, exist_ok=True)
            path = os.path.join(
                self.postmortem_dir,
                f"supervisor-death-{self.deaths:03d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"rc": rc,
                           "signal": -rc if rc < 0 else None,
                           "uptime_s": round(uptime_s, 3),
                           "deaths": self.deaths,
                           "wall_time": time.time(),
                           "cmd": self.cmd}, f, indent=2)
            os.replace(tmp, path)
        except OSError:
            pass

    # ---- run loop ----------------------------------------------------
    def request_stop(self) -> None:
        """Stop supervising: terminate the child and return its rc."""
        self._stop.set()
        child = self.child
        if child is not None and child.poll() is None:
            try:
                child.terminate()
            except OSError:
                pass

    def _wait(self) -> int:
        while True:
            try:
                return self.child.wait(timeout=0.2)
            except subprocess.TimeoutExpired:
                if self._stop.is_set():
                    try:
                        self.child.terminate()
                    except OSError:
                        pass
                    try:
                        return self.child.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        self.child.kill()
                        return self.child.wait()

    def run(self, install_signals: bool = True) -> int:
        """Supervise until the child completes cleanly, the budget is
        exhausted, or a stop is requested. Returns the final exit code."""
        prev: dict = {}
        if install_signals:
            def _on_signal(*_):
                self.request_stop()
                for s, h in prev.items():
                    signal.signal(s, h)

            try:
                for sig in (signal.SIGTERM, signal.SIGINT):
                    prev[sig] = signal.signal(sig, _on_signal)
            except ValueError:
                prev = {}  # not the main thread: caller owns signals
        consecutive_fast = 0
        try:
            while True:
                t0 = time.monotonic()
                # restart-lineage handoff (ISSUE 20 satellite): each
                # incarnation learns how many predecessors died and how
                # the last one went, and its FleetPublisher carries both
                # on the plane — a supervised-restart rejoin is then
                # distinguishable from a cold rejoin at the aggregator
                env = dict(self.env if self.env is not None
                           else os.environ)
                env["RTAP_SUPERVISED_RESTARTS"] = str(self.deaths)
                if self.death_rcs:
                    env["RTAP_SUPERVISED_LAST_RC"] = \
                        str(self.death_rcs[-1])
                self.child = subprocess.Popen(self.cmd, env=env)
                rc = self._wait()
                uptime = time.monotonic() - t0
                if self._stop.is_set():
                    self._event({"event": "supervisor_stopped", "rc": rc})
                    return rc
                if rc == 0:
                    self._event({"event": "serve_child_completed",
                                 "uptime_s": round(uptime, 3),
                                 "deaths": self.deaths})
                    return 0
                if rc == 2:
                    # usage/config error (argparse, bad flag values):
                    # deterministic and unhealable by restarting — fail
                    # fast instead of burning the budget on doomed
                    # respawns that bury the real flag error
                    self._event({"event": "serve_child_config_error",
                                 "rc": rc, "uptime_s": round(uptime, 3)})
                    return rc
                self.deaths += 1
                self.death_rcs.append(rc)
                self.kill_signals.append(-rc if rc < 0 else 0)
                self._event({"event": "serve_child_died", "rc": rc,
                             "signal": -rc if rc < 0 else None,
                             "uptime_s": round(uptime, 3),
                             "deaths": self.deaths})
                self._death_marker(rc, uptime)
                if self.deaths > self.restart_budget:
                    self._event({"event": "supervisor_budget_exhausted",
                                 "deaths": self.deaths,
                                 "budget": self.restart_budget})
                    return BUDGET_EXHAUSTED_RC
                consecutive_fast = (consecutive_fast + 1
                                    if uptime < self.healthy_after_s else 1)
                delay = min(self.backoff_max_s,
                            self.backoff_base_s
                            * (2 ** (consecutive_fast - 1)))
                self._obs_restarts.inc()
                self._obs_restarts_cum.inc()
                self._event({"event": "serve_child_restarting",
                             "delay_s": round(delay, 3),
                             "restart": self.deaths})
                if self._stop.wait(delay):
                    return rc
        finally:
            for sig, h in prev.items():
                try:
                    signal.signal(sig, h)
                except ValueError:
                    pass
