"""Hot-standby replication: journal shipping, fenced failover (ISSUE 8).

The PR 5 journal made a serve process RESUMABLE on the same host; this
module makes the fleet survive losing the host's PROCESS entirely, with
warm state: the leader's :class:`~rtap_tpu.resilience.journal.TickJournal`
appends are teed — the exact framed ``RJ`` record bytes — over a
persistent socket to a standby process that applies every shipped tick
through the NORMAL journal-replay scoring path (the same
dispatch/collect calls live_loop's replay uses), so its model state is
bit-identical to the leader's by construction. HTM state is cheap to
keep warm but expensive to rebuild (SDR capacity lives in accumulated
synapse state, not in any single tick — PAPERS.md 1503.07469): the
standby is always at the live edge, and takeover is a lease flip, not a
cold replay.

Topology and roles
------------------
One leader, one standby (``serve --replicate-to HOST:PORT`` /
``serve --standby --replicate-listen PORT``), sharing the alert sink
and checkpoint dir (single host or shared storage; a multi-host sink
needs an epoch-checking alert service in front — docs/RESILIENCE.md).

- **Leader**: journal appends tee into a bounded drop-oldest send
  buffer drained by a sender thread — a slow or dead standby can NEVER
  stall the leader's tick (``rtap_obs_repl_*`` counters size the lag).
  Journal compaction is clamped to the standby's acked position while
  one is connected (the PR 5 pause rule); a reconnecting standby whose
  position was compacted away takes the full-checkpoint fallback: the
  leader sends ``SNAP`` and the standby reloads the shared checkpoint
  dir, then re-requests the stream from its new position.
- **Standby**: applies TICK/FRAME records in order (appending them to
  its OWN journal first — the mirror is durable too), acks its
  position, tracks the leader's alert-delivery CURSOR records, and
  emits NOTHING while following: alert lines it would have written are
  buffered per tick and pruned as cursors confirm delivery.

Failover
--------
Leadership is a lease file (JSON ``{epoch, owner, ts, ...}``) the
leader refreshes every tick. The standby promotes when the lease goes
stale: it bumps the monotonic **fencing epoch** (the same
epoch-discipline as PR 5's ``alert_epoch`` and PR 6's ``run_epoch`` —
a rewound/reborn timeline never reuses identity), splices the alert
stream exactly-once (scan the sink past the last cursor into a
suppression set — the PR 5 resume scan — then flush only the buffered
lines the dead leader never delivered), checkpoints its warm fleet at
the takeover tick, and serves live. A paused old leader that wakes up
finds the epoch advanced and is FENCED: the loop breaks
(``leader_fenced``), the AlertWriter's fence guard refuses every
further sink write, serve exits :data:`FENCED_RC`, and its
BinaryBatchSource pushes a MAP naming the new leader so RB1 producers
re-point (``__leader__`` — docs/INGEST.md).

Wire format: the journal's own ``RJ`` record framing
(``RJ | type u8 | len u32 | payload | crc32``), CRC-checked and
torn-tail tolerant on both sides; control records (HELLO/ACK/SNAP) use
reserved type codes that never land in a journal file. A corrupt
record on the wire is skipped by CRC, surfaces as a tick gap, and the
standby re-requests the stream from its position (the leader re-reads
its journal from disk) — ``scripts/failover_soak.py`` proves the whole
story under kill -9 with bit-identical final state and exactly-once
alert ids.

Static membership: replication requires a fixed fleet (serve rejects
``--auto-register``/``--auto-release-after`` with replication flags) —
elastic membership under replication is future work.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
from collections import deque
from pathlib import Path

import numpy as np

from rtap_tpu.obs import get_registry
from rtap_tpu.resilience.journal import (
    _CRC,
    _CURSOR,
    _FRAME,
    _HEADER,
    _MAGIC,
    _MAX_PAYLOAD,
    _TICK,
    JournaledFrames,
    TickJournal,
    first_journal_tick,
    iter_raw_records,
)

__all__ = ["FENCED_RC", "FencingLease", "Lease", "ReplicationSender",
           "StandbyFollower", "WIRE_HELLO", "WIRE_ACK", "WIRE_SNAP",
           "WireWalker", "pack_wire"]

#: serve's exit code when a leader discovers it has been fenced out by a
#: promoted standby (distinct from crashes, budget exhaustion, and the
#: chaos proc_exit code)
FENCED_RC = 7

#: wire-only record types (never written to a journal file; the journal
#: types 1..3 pass through verbatim)
WIRE_HELLO = 16  # standby -> leader: payload <q> = first tick I need
WIRE_ACK = 17    # standby -> leader: payload <q> = tick applied+journaled
WIRE_SNAP = 18   # leader -> standby: payload <q> = checkpoint tick to
# fetch from the SHARED checkpoint dir (the journal can no longer
# backfill your position); re-HELLO after loading
_WIRE_TYPES = (_TICK, _CURSOR, _FRAME, WIRE_HELLO, WIRE_ACK, WIRE_SNAP)
_Q = struct.Struct("<q")


def pack_wire(typ: int, payload: bytes) -> bytes:
    """Frame a control record in the journal's RJ framing."""
    import zlib

    head = _HEADER.pack(_MAGIC, typ, len(payload))
    return head + payload + _CRC.pack(zlib.crc32(head[2:] + payload))


class WireWalker:
    """Incremental RJ-record stream walker (the replication socket's
    consumer): feed() recv chunks, get ``(typ, payload)`` records out.
    Torn tails wait for more bytes; bad magic/type/CRC resyncs to the
    next magic (counted — the chaos ``corrupt_bytes`` fault lands
    here and surfaces as a tick gap upstream, never as corruption)."""

    def __init__(self):
        self._buf = bytearray()
        self.records = 0
        self.garbage_bytes = 0
        self.bad_crc = 0

    def feed(self, data: bytes) -> list[tuple[int, bytes]]:
        import zlib

        self._buf += data
        buf = bytes(self._buf)
        n = len(buf)
        out: list[tuple[int, bytes]] = []
        off = 0
        while off + _HEADER.size + _CRC.size <= n:
            magic, typ, ln = _HEADER.unpack_from(buf, off)
            if magic != _MAGIC or typ not in _WIRE_TYPES \
                    or ln > _MAX_PAYLOAD:
                nxt = buf.find(_MAGIC, off + 1)
                skip_to = nxt if nxt != -1 else max(off + 1, n - 1)
                self.garbage_bytes += skip_to - off
                off = skip_to
                continue
            end = off + _HEADER.size + ln + _CRC.size
            if end > n:
                break  # torn tail: wait for more bytes
            payload = buf[off + _HEADER.size:end - _CRC.size]
            (crc,) = _CRC.unpack_from(buf, end - _CRC.size)
            if crc != zlib.crc32(buf[off + 2:off + _HEADER.size] + payload):
                self.bad_crc += 1
                nxt = buf.find(_MAGIC, off + 1)
                skip_to = nxt if nxt != -1 else max(off + 1, n - 1)
                self.garbage_bytes += skip_to - off
                off = skip_to
                continue
            out.append((typ, payload))
            off = end
        del self._buf[:off]
        self.records += len(out)
        return out


# ---------------------------------------------------------------- lease
class FencingLease:
    """The fencing-epoch state machine every lease backend shares: the
    sticky ``fenced`` flag, the loss/staleness predicates, the cached
    :meth:`still_mine` probe, the heartbeat thread, and the
    meta-rebinding discipline. Backends provide the storage — the file
    :class:`Lease` below, the control-plane
    ``rtap_tpu.fleet.control.ControlLease`` — by implementing
    :meth:`read`, :meth:`try_acquire` and :meth:`refresh`; everything
    that makes fencing CORRECT (once fenced always fenced, epoch
    comparison, probe caching) lives here exactly once."""

    def __init__(self, owner: str, timeout_s: float = 5.0,
                 meta: dict | None = None):
        if timeout_s <= 0:
            raise ValueError(f"lease timeout_s must be > 0; got {timeout_s}")
        self.owner = str(owner)
        self.timeout_s = float(timeout_s)
        self.meta = dict(meta or {})
        self.epoch = 0
        self.fenced = False
        self.refreshes = 0
        # still_mine() is called per alert batch: cache the backend
        # probe to at most one read per min(0.2, timeout/4) seconds
        self._probe_interval = min(0.2, self.timeout_s / 4.0)
        self._last_probe = 0.0
        self._lock = threading.Lock()
        self._hb_stop: threading.Event | None = None
        self._hb_thread: threading.Thread | None = None

    # ---- backend surface (subclasses implement) -----------------------
    def read(self) -> dict | None:
        """Current lease entry (``{epoch, owner, ts, ...}``) or None."""
        raise NotImplementedError

    def try_acquire(self) -> bool:
        raise NotImplementedError

    def refresh(self) -> bool:
        raise NotImplementedError

    # ---- shared fencing logic -----------------------------------------
    def _stale(self, cur: dict) -> bool:
        return time.time() - float(cur.get("ts", 0)) > self.timeout_s

    def is_stale(self) -> bool:
        """True when nobody is refreshing the lease (the standby's
        promotion trigger)."""
        cur = self.read()
        return cur is None or self._stale(cur)

    def _lost(self, cur: dict | None) -> bool:
        if cur is None:
            return False  # unreadable/missing: not evidence of a taker
        if int(cur.get("epoch", 0)) > self.epoch:
            return True
        return int(cur.get("epoch", 0)) == self.epoch \
            and cur.get("owner") != self.owner

    def start_heartbeat(self) -> "FencingLease":
        """Refresh from a daemon thread at timeout/3 so liveness means
        PROCESS alive, not tick-loop fast: a leader mid-checkpoint (a
        multi-second synchronous save on a slow host) must not go stale
        and get fenced by its own standby. SIGKILL and SIGSTOP silence
        the thread too — exactly the deaths the lease must expose. The
        thread reads before every write, so a woken zombie discovers
        the fence instead of clobbering the new leader's entry."""
        if self._hb_thread is not None:
            return self
        self._hb_stop = threading.Event()

        def _beat():
            while not self._hb_stop.is_set():
                if not self.refresh():
                    return  # fenced: never write again
                if self._hb_stop.wait(self.timeout_s / 3.0):
                    return

        self._hb_thread = threading.Thread(
            target=_beat, name="rtap-replicate-heartbeat", daemon=True)
        self._hb_thread.start()
        return self

    def stop_heartbeat(self) -> None:
        if self._hb_stop is not None:
            self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5.0)
            self._hb_thread = None

    def set_meta(self, **kv) -> None:
        """Update lease metadata AFTER the heartbeat is running. Rebinds
        ``self.meta`` to a fresh dict (never mutates in place): the
        heartbeat thread's write path unpacks ``**self.meta`` without a
        lock, and an in-place insert mid-iteration would raise and
        silently kill the thread — leaving lease freshness to the tick
        loop alone, the exact gap the heartbeat exists to cover."""
        with self._lock:
            self.meta = {**self.meta, **kv}

    def still_mine(self) -> bool:
        """Cheap cached ownership probe (the AlertWriter's fence)."""
        if self.fenced:
            return False
        now = time.monotonic()
        if now - self._last_probe < self._probe_interval:
            return True
        with self._lock:
            if self.fenced:
                return False
            self._last_probe = now
            if self._lost(self.read()):
                self.fenced = True
                return False
        return True

    def holder(self) -> str | None:
        cur = self.read()
        return cur.get("owner") if cur else None

    def holder_meta(self) -> dict:
        return self.read() or {}


class Lease(FencingLease):
    """File-based leadership lease with a monotonic fencing epoch.

    The holder rewrites ``{epoch, owner, ts, meta...}`` every refresh;
    a process whose refresh (or :meth:`still_mine` probe) finds the
    epoch advanced — or the owner changed at its own epoch — is FENCED
    for good (sticky: once fenced, always fenced). Acquiring a stale or
    absent lease BUMPS the epoch, which is what fences the previous
    holder. Single-standby topology: the acquire path is
    read-check-replace, not a distributed lock (docs/RESILIENCE.md
    names the deployment constraint)."""

    def __init__(self, path: str | Path, owner: str,
                 timeout_s: float = 5.0, meta: dict | None = None):
        super().__init__(owner, timeout_s=timeout_s, meta=meta)
        self.path = Path(path)
        #: highest epoch ever observed in the file — the acquire bump
        #: floor. Without it, one unreadable read (transient shared-fs
        #: fault, deleted file) at promotion would restart epochs at 1,
        #: INVERTING the fence: the old leader at epoch N>1 keeps
        #: serving and the new one fences itself.
        self._seen_epoch = 0
        # the seen-epoch floor gets its OWN lock: read() runs both
        # inside self._lock (refresh/still_mine) and without it
        # (is_stale, holder — the follower's stale probe), so reusing
        # self._lock here would deadlock the locked callers
        self._seen_lock = threading.Lock()

    def read(self) -> dict | None:
        try:
            cur = json.loads(self.path.read_text())
        except (OSError, ValueError):
            return None
        try:
            seen = int(cur.get("epoch", 0))
        except (TypeError, ValueError):
            # a malformed epoch field cannot advance the floor; the
            # entry itself still serves the caller's staleness logic
            return cur
        # the floor update is a read-modify-write shared between the
        # heartbeat thread (refresh -> read) and unlocked main-side
        # probes (is_stale/holder): unguarded, an interleaving could
        # REGRESS the floor (T2 loads the old floor, T1 stores a higher
        # one, T2 stores the stale max) — and a regressed floor at
        # promotion re-inverts the fence the floor exists to prevent
        with self._seen_lock:
            self._seen_epoch = max(self._seen_epoch, seen)
        return cur

    def _write(self) -> None:
        tmp = self.path.with_name(self.path.name + f".tmp-{os.getpid()}")
        tmp.write_text(json.dumps({"epoch": self.epoch, "owner": self.owner,
                                   "ts": time.time(), **self.meta}))
        os.replace(tmp, self.path)

    def try_acquire(self) -> bool:
        """Claim leadership: succeeds when the lease is absent, stale,
        or already ours. A fresh claim bumps the epoch past the previous
        holder's — the fence."""
        if self.fenced:
            return False
        cur = self.read()
        if cur is not None and cur.get("owner") != self.owner \
                and not self._stale(cur):
            return False
        if cur is not None and cur.get("owner") == self.owner:
            self.epoch = max(self.epoch, int(cur.get("epoch", 0)))
        else:
            self.epoch = max(int(cur.get("epoch", 0) if cur else 0),
                             self._seen_epoch, self.epoch) + 1
        try:
            self._write()
        except OSError:
            return False
        return True

    def refresh(self) -> bool:
        """Re-stamp ts, or discover the fence. Returns False exactly
        when fenced. Thread-safe: the tick loop's fence check and the
        heartbeat thread share it."""
        with self._lock:
            if self.fenced:
                return False
            if self._lost(self.read()):
                self.fenced = True
                return False
            try:
                self._write()
            except OSError:  # rtap: allow[except-silent] — an
                # unwritable lease is an infrastructure fault, not a
                # fence; keep serving (the standby will promote on
                # staleness and THEN we fence — the safe order)
                pass
            self.refreshes += 1
            self._last_probe = time.monotonic()
            return True


# --------------------------------------------------------------- sender
class ReplicationSender:
    """The leader half: tee journal records into a bounded buffer, ship
    them to the standby from a daemon thread, track acks, clamp
    compaction. The tick path's only cost is one deque append under a
    lock — socket stalls, reconnects, and backfills all live on the
    sender thread (``stall_socket`` chaos proves the non-stall
    property)."""

    #: tick-carrying types (dedup between disk backfill and live queue)
    _DATA_TYPES = (_TICK, _FRAME, _CURSOR)

    def __init__(self, address, journal: TickJournal,
                 checkpoint_dir: str | None = None,
                 max_buffer: int = 8192, chaos=None,
                 connect_timeout_s: float = 2.0):
        if max_buffer < 16:
            raise ValueError(f"max_buffer must be >= 16; got {max_buffer}")
        self.address = (address[0], int(address[1]))
        self.journal = journal
        self.checkpoint_dir = checkpoint_dir
        self.max_buffer = int(max_buffer)
        self.chaos = chaos
        self.connect_timeout_s = float(connect_timeout_s)
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._wire = WireWalker()
        self.connected = False
        self.acked_tick = -1
        self.shipped_records = 0
        self.shipped_bytes = 0
        self.dropped_records = 0
        self.send_errors = 0
        self.snapshot_fallbacks = 0
        self.backfilled_records = 0
        obs = get_registry()
        self._obs_shipped = obs.counter(
            "rtap_obs_repl_shipped_records_total",
            "journal records shipped to the standby (live tee + disk "
            "backfill)")
        self._obs_bytes = obs.counter(
            "rtap_obs_repl_shipped_bytes_total",
            "replication bytes shipped to the standby")
        self._obs_dropped = obs.counter(
            "rtap_obs_repl_dropped_records_total",
            "journal records dropped from the bounded send buffer "
            "(drop-oldest: a slow/absent standby never stalls the "
            "leader; the standby heals via disk backfill on reconnect)")
        self._obs_errors = obs.counter(
            "rtap_obs_repl_send_errors_total",
            "replication socket errors (each starts a reconnect cycle)")
        self._obs_snap = obs.counter(
            "rtap_obs_repl_snapshot_fallbacks_total",
            "standby reconnects whose position was compacted out of the "
            "journal — resynced via the shared-checkpoint fetch")
        self._obs_backfill = obs.counter(
            "rtap_obs_repl_backfilled_records_total",
            "records re-read from the journal on disk to catch a "
            "reconnecting standby up")
        self._obs_lag = obs.gauge(
            "rtap_obs_repl_lag_records",
            "records waiting in the replication send buffer")
        self._obs_acked = obs.gauge(
            "rtap_obs_repl_acked_tick",
            "highest tick the standby has acked (applied + journaled)")

    # ---- the journal tee (loop thread) -------------------------------
    def tee(self, typ: int, tick: int, rec: bytes) -> None:
        with self._cond:
            self._q.append((typ, tick, rec))
            while len(self._q) > self.max_buffer:
                self._q.popleft()
                self.dropped_records += 1
                self._obs_dropped.inc()
            self._obs_lag.set(len(self._q))
            self._cond.notify()

    def compact_floor(self):
        """Journal compaction clamp: while a standby is CONNECTED the
        leader may not drop ticks past its ack (pause rule); with no
        standby attached the clamp lifts (bounded disk growth — the
        reconnect path heals via backfill or checkpoint fetch)."""
        return (self.acked_tick + 1) if self.connected else None

    # ---- lifecycle ----------------------------------------------------
    def start(self) -> "ReplicationSender":
        self._thread = threading.Thread(
            target=self._run, name="rtap-replicate-sender", daemon=True)
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def ack_lag_ticks(self) -> float | None:
        """Leader-side replication-ack lag in ticks (the journal's next
        write position minus the standby's last ack) — the first-class
        lag gauge the latency layer polls (ISSUE 11). None until a
        standby has acked at least once (no standby = no lag story)."""
        if self.acked_tick < 0:
            return None
        return float(max(0, self.journal.next_tick - 1 - self.acked_tick))

    def stats(self) -> dict:
        return {
            "connected": self.connected,
            "acked_tick": self.acked_tick,
            "shipped_records": self.shipped_records,
            "shipped_bytes": self.shipped_bytes,
            "dropped_records": self.dropped_records,
            "send_errors": self.send_errors,
            "snapshot_fallbacks": self.snapshot_fallbacks,
            "backfilled_records": self.backfilled_records,
            "buffered": len(self._q),
        }

    # ---- sender thread -----------------------------------------------
    def _run(self) -> None:
        backoff = 0.05
        while not self._stop.is_set():
            try:
                sock = socket.create_connection(
                    self.address, timeout=self.connect_timeout_s)
            except OSError:
                if self._stop.wait(backoff):
                    return
                backoff = min(1.0, backoff * 2)
                continue
            backoff = 0.05
            self._wire = WireWalker()  # no stale partial frames across
            # connections
            try:
                self._serve_conn(sock)
            except OSError:
                self.send_errors += 1
                self._obs_errors.inc()
            finally:
                self.connected = False
                try:
                    sock.close()
                except OSError:
                    pass

    def _ship(self, sock, tick: int, rec: bytes) -> None:
        data = rec
        if self.chaos is not None:
            # the chaos wire seam: may sleep (stall_socket — proves the
            # tick never stalls), raise (conn_drop — proves reconnect +
            # backfill), or corrupt bytes (corrupt_bytes — proves the
            # standby's CRC skip + resync request)
            data = self.chaos.on_wire(tick, data)
        sock.sendall(data)
        self.shipped_records += 1
        self.shipped_bytes += len(data)
        self._obs_shipped.inc()
        self._obs_bytes.inc(len(data))

    def _poll_inbound(self, sock) -> int | None:
        """Drain any standby->leader records without blocking; returns a
        HELLO tick when the standby requested a (re)stream."""
        hello = None
        while True:
            try:
                sock.setblocking(False)
                data = sock.recv(1 << 16)
            except (BlockingIOError, InterruptedError):
                break
            finally:
                sock.settimeout(0.2)
            if not data:
                raise ConnectionError("standby closed the connection")
            for typ, payload in self._wire.feed(data):
                if typ == WIRE_ACK and len(payload) >= 8:
                    self.acked_tick = max(self.acked_tick,
                                          _Q.unpack_from(payload)[0])
                    self._obs_acked.set(self.acked_tick)
                elif typ == WIRE_HELLO and len(payload) >= 8:
                    hello = int(_Q.unpack_from(payload)[0])
        return hello

    def _await_hello(self, sock) -> int:
        deadline = time.monotonic() + 30.0
        sock.settimeout(0.2)
        while not self._stop.is_set() and time.monotonic() < deadline:
            try:
                data = sock.recv(1 << 16)
            except socket.timeout:
                continue
            if not data:
                raise ConnectionError("standby closed before HELLO")
            hello = None
            for typ, payload in self._wire.feed(data):
                if typ == WIRE_HELLO and len(payload) >= 8:
                    hello = int(_Q.unpack_from(payload)[0])
                elif typ == WIRE_ACK and len(payload) >= 8:
                    self.acked_tick = max(self.acked_tick,
                                          _Q.unpack_from(payload)[0])
            if hello is not None:
                return hello
        raise ConnectionError("no HELLO from standby")

    #: identical-position HELLOs tolerated before escalating to the
    #: checkpoint fallback: a standby stuck re-requesting the SAME tick
    #: means the journal cannot serve it (a mid-journal fault ate the
    #: records) — re-reading the same hole forever would be a livelock
    MAX_STALLED_HELLOS = 3

    def _serve_conn(self, sock) -> None:
        pending_hello: int | None = self._await_hello(sock)
        self.connected = True
        stalled_at: int | None = None
        stalled = 0
        while not self._stop.is_set():
            start = pending_hello
            pending_hello = None
            if start is not None:
                if start == stalled_at:
                    stalled += 1
                else:
                    stalled_at, stalled = start, 0
                first = first_journal_tick(self.journal.path)
                if (first >= 0 and start < first) \
                        or stalled >= self.MAX_STALLED_HELLOS:
                    # the standby's position was compacted away: the
                    # full-checkpoint fallback (it reloads the SHARED
                    # checkpoint dir, then re-HELLOs from there)
                    from rtap_tpu.service.checkpoint import peek_resume_ticks

                    ck = peek_resume_ticks(self.checkpoint_dir) \
                        if self.checkpoint_dir else 0
                    self._ship(sock, start,
                               pack_wire(WIRE_SNAP, _Q.pack(int(ck))))
                    self.snapshot_fallbacks += 1
                    self._obs_snap.inc()
                    pending_hello = self._await_hello(sock)
                    continue
                self._sent_data = start - 1
                self._sent_cursor = start - 1
                # disk backfill: the journal IS the retransmit buffer
                for typ, tick, rec in iter_raw_records(
                        self.journal.path, start):
                    if self._stop.is_set():
                        return
                    self._ship(sock, tick, rec)
                    self.backfilled_records += 1
                    self._obs_backfill.inc()
                    if typ == _CURSOR:
                        self._sent_cursor = max(self._sent_cursor, tick)
                    else:
                        self._sent_data = max(self._sent_data, tick)
                    hello = self._poll_inbound(sock)
                    if hello is not None:
                        pending_hello = hello
                        break
                if pending_hello is not None:
                    continue
            # live streaming from the tee queue
            pending_hello = self._stream_live(sock)
            if pending_hello is None:
                return

    def _stream_live(self, sock) -> int | None:
        # per-type high-water marks dedup the overlap between the disk
        # backfill and records the tee queued meanwhile (TICK/FRAME and
        # CURSOR share tick numbering, so they dedup separately — a
        # cursor for the tick just shipped must still go out)
        sent_data = getattr(self, "_sent_data", -1)
        sent_cursor = getattr(self, "_sent_cursor", -1)
        while not self._stop.is_set():
            with self._cond:
                if not self._q:
                    self._cond.wait(0.1)
                batch = []
                while self._q and len(batch) < 256:
                    batch.append(self._q.popleft())
                self._obs_lag.set(len(self._q))
            for typ, tick, rec in batch:
                if typ == _CURSOR:
                    if tick <= sent_cursor:
                        continue
                    sent_cursor = tick
                elif typ in (_TICK, _FRAME):
                    if tick <= sent_data:
                        continue
                    sent_data = tick
                self._ship(sock, tick, rec)
            self._sent_data, self._sent_cursor = sent_data, sent_cursor
            hello = self._poll_inbound(sock)
            if hello is not None:
                return hello
        return None


# ------------------------------------------------------------- follower
class _PromoteNow(Exception):
    """Internal: the lease went stale mid-follow."""

    def __init__(self, detect_s: float):
        self.detect_s = detect_s


class StandbyFollower:
    """The standby half: listen for the leader, mirror its journal,
    apply every tick through the normal scoring path, buffer undelivered
    alert lines, and promote on lease loss. Single-threaded; ``run()``
    blocks until promotion ("promoted") or a stop request ("stopped")."""

    def __init__(self, registry, journal: TickJournal, *, lease: Lease,
                 port: int = 0, host: str = "127.0.0.1",
                 alert_path: str | None = None,
                 checkpoint_dir: str | None = None, learn: bool = True,
                 cadence_s: float = 1.0, stop_event=None,
                 max_buffered_alerts: int = 65536):
        self.reg = registry
        self.journal = journal
        self.lease = lease
        self.alert_path = alert_path
        self.checkpoint_dir = checkpoint_dir
        self.learn = bool(learn)
        self.cadence_s = float(cadence_s)
        self.stop_event = stop_event
        self.max_buffered_alerts = int(max_buffered_alerts)
        self.host, self.port = host, int(port)
        self.address = None
        self.groups = registry.groups
        self.gpos: list[int] = []
        self.expected = 0
        self.applied = 0
        self.duplicates = 0
        self.resyncs = 0
        self.snap_failures = 0
        self.skipped_rows = 0
        self.buffered_dropped = 0
        self.last_cursor: tuple[int, int] | None = None  # (tick, offset)
        self._alert_buf: deque = deque()  # (tick, alert_id, line)
        self._last_record_t = time.monotonic()
        self._last_hello_t = 0.0
        self._stale_since = None  # first stale lease observation
        self.stale_log: list = []  # lease ages at stale observations
        self._table = None  # DispatchTable for FRAME decode, lazy
        self._routing = None
        obs = get_registry()
        self._obs_applied = obs.counter(
            "rtap_obs_repl_applied_ticks_total",
            "shipped ticks the standby applied through the scoring path")
        self._obs_resyncs = obs.counter(
            "rtap_obs_repl_resyncs_total",
            "stream re-requests the standby sent after a gap (dropped/"
            "corrupt records; the leader re-reads its journal)")
        self._obs_buffered = obs.gauge(
            "rtap_obs_repl_buffered_alerts",
            "alert lines buffered on the standby awaiting the leader's "
            "delivery cursor (flushed exactly-once at promotion)")
        self._obs_promoted = obs.counter(
            "rtap_obs_repl_promotions_total",
            "standby promotions to leader (lease takeover)")
        self._obs_garbage = obs.counter(
            "rtap_obs_repl_wire_garbage_bytes_total",
            "replication stream bytes skipped while resyncing to the "
            "next record magic (corrupt producers, line noise)")

    # ---- catch-up from local disk -------------------------------------
    def _adopt_checkpoints(self, attempts: int = 8) -> bool:
        """Load the shared checkpoint dir into the registry (the loop's
        resume pattern, reduced to static membership). Returns True if
        any group was loaded.

        Retries per group: unlike every other resume path, the standby
        reads this dir while the LIVE leader may be saving to it — the
        atomic swap (rename + old-copy sweep) can delete files under an
        in-progress orbax read, which fails loudly, never silently; a
        re-read lands on the new complete copy. A torn adoption across
        groups (different save rounds) is fine — per-group ``gpos``
        positions each group and the stream converges them."""
        if not self.checkpoint_dir or not os.path.isdir(self.checkpoint_dir):
            return False
        from rtap_tpu.service.checkpoint import load_group, validate_resume
        from rtap_tpu.service.shardpath import group_checkpoint_path

        loaded = False
        for gi, grp in enumerate(self.groups):
            ck_path = group_checkpoint_path(self.checkpoint_dir, gi)
            if not os.path.isdir(ck_path):
                continue
            for attempt in range(attempts):
                try:
                    resumed = load_group(ck_path, mesh=grp.mesh)
                    resumed.health = getattr(grp, "health", False)
                    validate_resume(resumed, ck_path, grp,
                                    allow_claimed_extras=not self.learn)
                    break
                except Exception:  # noqa: BLE001 — mid-swap read race
                    if attempt == attempts - 1:
                        raise
                    time.sleep(0.25)
            self.groups[gi] = resumed
            for slot in self.reg._slots.values():
                if slot.group is grp:
                    slot.group = resumed
            loaded = True
        return loaded

    def _build_routing(self):
        maps, off = [], 0
        for g in self.groups:
            slots = g.live_slots()
            maps.append((slots, [g.stream_ids[i] for i in slots], off))
            off += len(slots)
        self._routing = maps
        self.width = off

    def _reposition_from_checkpoints(self) -> bool:
        """Adopt the shared checkpoints and derive stream position from
        them (the one implementation behind BOTH the startup catch-up
        and the SNAP reconnect fallback — they must never diverge):
        per-group gpos from the saved global journal cursors, routing,
        the HELLO frontier, and the suppression base (the adopting
        checkpoints' alert cursor). A local mirror tail extending
        beyond the adopted position is discarded — after a failover it
        belongs to the pre-takeover timeline, and keeping it would let
        a returning standby replay rows the live leader never served.
        Returns whether any checkpoint was adopted."""
        loaded = self._adopt_checkpoints()
        self.gpos = [
            grp.resume_journal_tick
            if getattr(grp, "resume_journal_tick", None) is not None
            else grp.ticks
            for grp in self.groups
        ]
        self._build_routing()
        self._table = None
        self.expected = min(self.gpos) if self.gpos else 0
        off = None
        for g in self.groups:
            o = getattr(g, "resume_alerts_offset", None)
            if o is not None:
                off = o if off is None else min(off, o)
        if off is not None:
            self.last_cursor = (self.expected - 1, int(off))
        if self.journal.next_tick > self.expected:
            self.journal.wipe()
        else:
            self.journal.release_recovered()
        return loaded

    def _catch_up(self) -> None:
        """Initialize position from the SHARED checkpoints (the only
        authoritative restore point): the leader's stream backfills
        everything past them."""
        self._reposition_from_checkpoints()

    # ---- scoring (the normal path, m=1 chunks) ------------------------
    def _apply_row(self, jt: int, jts: int, jvals,
                   buffer_alerts: bool = True) -> None:
        from rtap_tpu.service.alerts import format_alert_line
        from rtap_tpu.service.loop import _alert_gid

        if isinstance(jvals, JournaledFrames):
            from rtap_tpu.ingest.dispatch import (
                DispatchTable,
                decode_frames_to_row,
            )

            if jvals.width != self.width:
                self.skipped_rows += 1
                return
            if self._table is None:
                self._table = DispatchTable.from_registry(self.reg)
            jvals = decode_frames_to_row([jvals.blob], jvals.width,
                                         self._table)
        else:
            jvals = np.asarray(jvals, np.float32)
        if len(jvals) != self.width:
            self.skipped_rows += 1
            return
        for gi, grp in enumerate(self.groups):
            if self.gpos[gi] != jt:
                continue  # a torn checkpoint adoption leaves groups at
                # different positions; each applies only its own next
                # row (expected == min(gpos), so ahead groups skip)
            slots, ids, off = self._routing[gi]
            v = np.full((1, grp.G) + jvals.shape[1:], np.nan, np.float32)
            v[0, slots] = jvals[off:off + len(slots)]
            t = np.full((1, grp.G), int(jts), np.int64)
            r_raw, r_ll, r_al = grp.collect_chunk(
                grp.dispatch_chunk(v, t, learn=self.learn))
            self.gpos[gi] += 1
            if buffer_alerts:
                gid = _alert_gid(gi, grp)
                for j in np.nonzero(r_al[0, slots])[0]:
                    sid = ids[j]
                    aid = f"{gid}:{sid}:{grp.ticks - 1}"
                    self._alert_buf.append((jt, aid, format_alert_line(
                        aid, sid, int(jts), jvals[off + int(j)],
                        float(r_raw[0, slots][j]),
                        float(r_ll[0, slots][j]))))
                while len(self._alert_buf) > self.max_buffered_alerts:
                    # cursors stopped coming (leader sink quarantined?):
                    # bounded memory wins; drop-oldest, counted
                    self._alert_buf.popleft()
                    self.buffered_dropped += 1
        self._obs_buffered.set(len(self._alert_buf))

    # ---- the follow loop ----------------------------------------------
    def _stale_check(self) -> None:
        # staleness must PERSIST for an extra timeout/2 before promoting:
        # a single stale read can be a live leader whose heartbeat
        # thread was starved for one beat (GIL/scheduler jitter on a
        # loaded host — observed during a peer's interpreter start-up),
        # and a false promotion fences a healthy leader. A genuinely
        # dead leader stays stale; the grace costs ~timeout/2 of
        # detection latency, budgeted in the lease-timeout guidance.
        cur = self.lease.read()
        if cur is None or self.lease._stale(cur):
            now = time.monotonic()
            # forensic trail for the promotion decision: what the lease
            # actually looked like (age, or unreadable) at each stale
            # observation — surfaced in stats()["stale_log"] so a
            # surprising takeover is attributable after the fact
            if len(self.stale_log) < 64:
                ts = cur.get("ts") if cur is not None else None
                self.stale_log.append(
                    round(time.time() - float(ts), 3)
                    if ts is not None else None)
            if self._stale_since is None:
                self._stale_since = now
            elif now - self._stale_since >= self.lease.timeout_s / 2.0:
                raise _PromoteNow(now - self._last_record_t)
        else:
            self._stale_since = None

    def _stopped(self) -> bool:
        return self.stop_event is not None and self.stop_event.is_set()

    def _send_hello(self, conn) -> None:
        conn.sendall(pack_wire(WIRE_HELLO, _Q.pack(int(self.expected))))

    def _request_resync(self, conn) -> None:
        now = time.monotonic()
        if now - self._last_hello_t < 0.5:
            return  # rate-limited: one request per gap episode
        self._last_hello_t = now
        self.resyncs += 1
        self._obs_resyncs.inc()
        self._send_hello(conn)

    def _handle(self, conn, typ: int, payload: bytes) -> None:
        if typ == WIRE_SNAP:
            # our position was compacted out of the leader's journal:
            # the full-checkpoint fetch — reload the shared dir, then
            # re-request the stream from the new position
            if not self._reposition_from_checkpoints():
                # shared dir empty/missing (the leader never saved a
                # round yet): stay ALIVE and keep asking from where we
                # are — a degraded-redundancy standby beats a dead one,
                # and the leader's next checkpoint round unblocks the
                # fallback. Counted, never a crash.
                self.snap_failures += 1
                time.sleep(0.25)
            else:
                self._alert_buf.clear()  # pre-checkpoint alerts were
                # delivered (the cursor in meta is at/after them)
                self._obs_buffered.set(0)
            self._last_hello_t = 0.0
            self._send_hello(conn)
            return
        rec = TickJournal._parse(typ, payload)
        if rec is None:
            return  # malformed payload inside a valid frame: drop
        if typ == _CURSOR:
            ct, coff = rec
            if self.last_cursor is None or ct >= self.last_cursor[0]:
                self.last_cursor = (int(ct), int(coff))
            self.journal.append_cursor(int(ct), int(coff))
            while self._alert_buf and self._alert_buf[0][0] <= ct:
                self._alert_buf.popleft()  # delivered by the leader
            self._obs_buffered.set(len(self._alert_buf))
            return
        jt, jts, jvals = rec
        if jt < self.expected:
            self.duplicates += 1
            return
        if jt > self.expected:
            self._request_resync(conn)
            return
        # mirror to the local journal FIRST (durability order matches
        # the leader's write-ahead), then score; guarded so a re-stream
        # over rows already mirrored never appends a duplicate index
        if jt >= self.journal.next_tick:
            if isinstance(jvals, JournaledFrames):
                self.journal.append_tick_frames(jt, jts, jvals.width,
                                                [jvals.blob])
            else:
                self.journal.append_tick(jt, jts, jvals)
        self._apply_row(jt, jts, jvals)
        self.expected = jt + 1
        self.applied += 1
        self._obs_applied.inc()
        self._last_record_t = time.monotonic()
        self._last_hello_t = 0.0
        conn.sendall(pack_wire(WIRE_ACK, _Q.pack(self.expected - 1)))

    def _follow_conn(self, conn) -> None:
        conn.settimeout(0.1)  # the recv timeout bounds lease-staleness
        # detection latency while a (dead) connection lingers
        self._send_hello(conn)
        wire = WireWalker()
        garbage0 = 0
        while not self._stopped():
            self._stale_check()
            try:
                data = conn.recv(1 << 20)
            except socket.timeout:
                continue
            if not data:
                return  # leader gone; lease watch decides what's next
            for typ, payload in wire.feed(data):
                self._handle(conn, typ, payload)
            if wire.garbage_bytes > garbage0:
                self._obs_garbage.inc(wire.garbage_bytes - garbage0)
                garbage0 = wire.garbage_bytes
                self._request_resync(conn)

    def run(self) -> str:
        """Follow until promoted or stopped. Returns "promoted" (the
        caller continues into live leader serving — checkpoints and the
        spliced alert stream are already on disk) or "stopped"."""
        self._catch_up()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(1)
        srv.settimeout(0.1)
        self.address = srv.getsockname()
        self._last_record_t = time.monotonic()
        try:
            while not self._stopped():
                try:
                    self._stale_check()
                    try:
                        conn, _addr = srv.accept()
                    except socket.timeout:
                        continue
                    try:
                        self._follow_conn(conn)
                    finally:
                        try:
                            conn.close()
                        except OSError:
                            pass
                except _PromoteNow as p:
                    self._stale_since = None
                    if self.lease.try_acquire():
                        # hold the lease ALIVE through the promotion
                        # itself: the splice + warm-fleet checkpoint can
                        # take multi-second on a slow host, and a
                        # restarted peer finding a stale entry would
                        # steal leadership from us mid-takeover
                        self.lease.start_heartbeat()
                        self._promote(p.detect_s)
                        return "promoted"
                    # lost the race (another standby won): keep following
                    self._last_record_t = time.monotonic()
                except OSError:
                    continue  # connection-level fault: re-accept
            return "stopped"
        finally:
            try:
                srv.close()
            except OSError:
                pass

    # ---- promotion -----------------------------------------------------
    def _promote(self, detect_s: float) -> None:
        """Take over: splice the alert stream exactly-once, checkpoint
        the warm fleet at the takeover tick, announce on the stream."""
        from rtap_tpu.service.alerts import heal_torn_tail, scan_alert_ids
        from rtap_tpu.service.loop import _save_all

        self.promote_detect_s = float(detect_s)
        re_emitted = suppressed = 0
        sink_size = 0
        #: alert ids the dead leader delivered for ticks we NEVER
        #: received (killed between its emit and its ship): our live
        #: loop will re-score those ticks — it must arm this residual
        #: suppression so the re-scored ids are never duplicated
        self.resume_suppression: set[str] = set()
        if self.alert_path is not None:
            # the dead leader may have torn its last line mid-write
            heal_torn_tail(self.alert_path)
            # exactly-once splice: every alert byte past the last
            # delivery cursor belongs to the buffered window — suppress
            # exactly the ids the dead leader already delivered, flush
            # the rest (the PR 5 resume-suppression scan, reused)
            base_off = self.last_cursor[1] if self.last_cursor else 0
            suppress = scan_alert_ids(self.alert_path, base_off)
            buffered_ids = {aid for _t, aid, _l in self._alert_buf}
            self.resume_suppression = suppress - buffered_ids
            try:
                with open(self.alert_path, "a") as f:
                    for _tick, aid, line in self._alert_buf:
                        if aid in suppress:
                            suppressed += 1
                            continue
                        f.write(line)
                        re_emitted += 1
                    f.write(json.dumps({
                        "event": "standby_promoted",
                        "tick": int(self.expected),
                        "epoch": int(self.lease.epoch),
                        "detect_s": round(detect_s, 3),
                        "detect_ticks": round(detect_s / self.cadence_s, 2)
                        if self.cadence_s > 0 else None,
                        "re_emitted": re_emitted,
                        "suppressed": suppressed,
                    }) + "\n")
                    f.flush()
            except OSError:  # rtap: allow[except-silent] —
                # non-fatal sink discipline, like the live loop's:
                # the splice is retried by the next resume scan
                pass
            try:
                sink_size = os.path.getsize(self.alert_path)
            except OSError:
                sink_size = 0
        self._alert_buf.clear()
        self._obs_buffered.set(0)
        self.promote_re_emitted = re_emitted
        self.promote_suppressed = suppressed
        if self.checkpoint_dir:
            # the takeover checkpoint: the warm fleet at the spliced
            # instant, so the caller's live_loop resumes bit-identically
            # (and a crash right after promotion replays nothing stale)
            _save_all(self.groups, self.checkpoint_dir,
                      alerts_offset=sink_size, journal_tick=self.expected)
        self._obs_promoted.inc()

    def stats(self) -> dict:
        return {
            "applied_ticks": self.applied,
            "duplicates": self.duplicates,
            "resyncs": self.resyncs,
            "snap_failures": self.snap_failures,
            "skipped_rows": self.skipped_rows,
            "buffered_alerts": len(self._alert_buf),
            "buffered_dropped": self.buffered_dropped,
            "expected_tick": self.expected,
            "last_cursor": list(self.last_cursor) if self.last_cursor
            else None,
            "stale_log": list(self.stale_log),
        }
