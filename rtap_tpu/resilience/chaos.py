"""Deterministic, seedable fault injection for the serve stack.

Every recovery path in the resilience layer is exercised end-to-end, not
trusted: a :class:`ChaosSpec` (hand-written JSON or generated from a seed)
schedules faults against the live loop's IO seams — the source callable,
per-group dispatch/collect, the alert sink's file object, and checkpoint
saves — and a :class:`ChaosEngine` injects them at exactly the scripted
ticks. Same seed, same schedule, same injection points: a chaos soak that
found a bug is a reproducer, not an anecdote
(``scripts/chaos_soak.py --seed N``; ``serve --chaos-spec FILE``).

Fault vocabulary (``Fault.kind``):

- ``source_timeout``      — the poll yields NaN for the targeted stream
  indices (``streams``; None = the whole vector) — a timed-out exporter
- ``source_malformed``    — the source raises ``ValueError`` (garbage
  payload reached the adapter)
- ``source_conn_drop``    — the source raises ``ConnectionResetError``
- ``source_backwards_ts`` — the poll's timestamp jumps back ``ts_skew_s``
  seconds (a misbehaving exporter clock)
- ``dispatch_exception``  — group ``group``'s dispatch raises
- ``collect_exception``   — group ``group``'s collect raises
- ``dispatch_hang``       — group ``group``'s dispatch blocks ``seconds``
  (a wedged device RPC, scaled down to test budget)
- ``alert_sink_oserror``  — every alert-file write raises ``OSError``
  (full disk) for the fault window
- ``checkpoint_oserror``  — the per-group checkpoint save raises
  ``OSError`` for the fault window
- ``proc_exit``           — the PROCESS dies abruptly (``os._exit``, no
  cleanup, no flush) at the tick boundary right after the tick's row is
  ingested/journaled — the durability layer's honest crash (ISSUE 5;
  ``scripts/chaos_soak.py --supervise`` runs this under the supervisor
  + journal recovery path). Excluded from seed-GENERATED schedules
  (it would kill the generating test run); schedule it explicitly.
- ``conn_drop`` / ``stall_socket`` / ``corrupt_bytes`` — network faults
  on the byte-stream edges (ISSUE 8): the replication channel and the
  binary-ingest wire consult ``ChaosEngine.on_wire`` per shipped
  record. Excluded from the default generated draw (they fire only
  where a wire seam exists, and adding them would shift existing
  seeds' digests); ``scripts/chaos_soak.py --replication`` exercises
  them against a live leader/standby pair.
- ``topology_burst``      — the poll ADDS ``magnitude`` to the targeted
  stream indices for the fault window: a correlated multi-stream fault
  (a real blast radius, not one exporter misbehaving) for the incident-
  correlation drill (ISSUE 9; ``scripts/chaos_soak.py
  --topology-burst`` schedules one spanning multiple groups and asserts
  exactly ONE cluster-level incident pages, not N per-stream alerts).
  Excluded from generated schedules: an undirected burst has no
  topology to correlate — schedule it explicitly with the stream
  indices of the adjacent nodes it floods.

A fault is active for ticks ``[tick, tick + duration)``. Group-targeted
kinds apply to every group when ``group`` is None. The engine logs every
actual injection (``engine.injected``) and counts them in
``rtap_obs_chaos_injected_total{kind=...}`` so a chaos run's artifact
states what was injected, not just what was scheduled.
"""

from __future__ import annotations

import hashlib
import json
import random
import time
from dataclasses import asdict, dataclass, field

import numpy as np

from rtap_tpu.obs import get_registry

__all__ = ["ChaosEngine", "ChaosError", "ChaosSpec", "FAULT_KINDS",
           "Fault", "GENERATED_KINDS", "PROC_EXIT_CODE"]

FAULT_KINDS = (
    "source_timeout",
    "source_malformed",
    "source_conn_drop",
    "source_backwards_ts",
    "dispatch_exception",
    "collect_exception",
    "dispatch_hang",
    "alert_sink_oserror",
    "checkpoint_oserror",
    "proc_exit",
    # network fault kinds for the byte-stream edges (ISSUE 8): the
    # replication channel and the binary-ingest wire share one seam —
    # ChaosEngine.on_wire(tick, data) — so both paths prove the same
    # recovery vocabulary (CRC skip + resync/backfill, reconnect,
    # bounded-buffer non-stall)
    "conn_drop",      # the wire send raises ConnectionResetError
    "stall_socket",   # the wire send blocks `seconds` (slow peer)
    "corrupt_bytes",  # bytes flip in flight (CRC must catch, never apply)
    # correlated multi-stream burst (ISSUE 9): the source adds
    # `magnitude` to the targeted stream indices for the window — the
    # incident-correlation drill's blast-radius fault
    "topology_burst",
)

#: kinds NOT in the default generated draw, in addition to keeping every
#: pre-ISSUE-5 seed's schedule byte-identical (digest-stable):
#: - proc_exit kills the process (ISSUE 5 — schedule it explicitly);
#: - the ISSUE 8 wire kinds only fire where a wire seam consults the
#:   engine (replication sender / binary feeders) — generating them
#:   into a plain serve schedule would inject nothing, and adding them
#:   to the draw would shift every existing seed's digest. Pass
#:   kinds=(..., "corrupt_bytes", ...) to generate() to draw them.
#: - topology_burst needs explicit stream targeting (a random draw has
#:   no topology to correlate) — schedule it by hand (ISSUE 9).
_UNGENERATED = ("proc_exit", "conn_drop", "stall_socket", "corrupt_bytes",
                "topology_burst")
GENERATED_KINDS = tuple(k for k in FAULT_KINDS if k not in _UNGENERATED)

#: exit code of an injected proc_exit death (distinguishable from real
#: crashes and from SIGKILL in supervisor logs)
PROC_EXIT_CODE = 86

#: kinds that target one StreamGroup (``group`` field; None = all groups)
GROUP_KINDS = ("dispatch_exception", "collect_exception", "dispatch_hang",
               "checkpoint_oserror")


class ChaosError(RuntimeError):
    """The injected dispatch/collect failure (distinguishable from real
    faults in logs and quarantine events by its message prefix)."""


@dataclass(frozen=True)
class Fault:
    kind: str
    tick: int
    duration: int = 1
    group: int | None = None
    streams: tuple[int, ...] | None = None  # source faults: vector indices
    seconds: float = 0.25  # dispatch_hang block length
    ts_skew_s: int = 3600  # source_backwards_ts jump
    magnitude: float = 12.0  # topology_burst value offset

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; known: {FAULT_KINDS}")
        if self.tick < 0 or self.duration < 1:
            raise ValueError(
                f"need tick >= 0 and duration >= 1; got {self.tick}, "
                f"{self.duration}")

    def active(self, tick: int, group: int | None = None) -> bool:
        if not self.tick <= tick < self.tick + self.duration:
            return False
        return self.group is None or group is None or self.group == group


@dataclass
class ChaosSpec:
    """A deterministic fault schedule: explicit list or seed-generated."""

    faults: list[Fault] = field(default_factory=list)
    seed: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "ChaosSpec":
        """Parse the ``--chaos-spec`` JSON shape: either
        ``{"seed": S, "faults": [{"kind": ..., "tick": ...}, ...]}`` or
        ``{"seed": S, "generate": {"n_ticks": T, "n_groups": G,
        "rate": R, "kinds": [...]}}``."""
        seed = int(d.get("seed", 0))
        if "generate" in d:
            if "faults" in d:
                raise ValueError(
                    "chaos spec takes 'faults' OR 'generate', not both")
            return cls.generate(seed=seed, **d["generate"])
        faults = [
            Fault(**{**f, "streams": tuple(f["streams"])
                     if f.get("streams") is not None else None})
            for f in d.get("faults", [])
        ]
        return cls(faults=faults, seed=seed)

    @classmethod
    def from_file(cls, path: str) -> "ChaosSpec":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    @classmethod
    def generate(cls, seed: int, n_ticks: int, n_groups: int = 1,
                 rate: float = 0.05,
                 kinds: tuple[str, ...] | None = None) -> "ChaosSpec":
        """Seed-deterministic schedule: each tick draws one fault with
        probability ``rate``, kind and target group uniform. The PRNG is
        a private ``random.Random(seed)`` — the global random state and
        wall clock never touch the schedule, so ``--seed N`` is a full
        reproducer of the injected fault sequence."""
        if not 0 <= rate <= 1:
            raise ValueError(f"rate must be in [0, 1]; got {rate}")
        kinds = tuple(kinds or GENERATED_KINDS)
        for k in kinds:
            if k not in FAULT_KINDS:
                raise ValueError(f"unknown fault kind {k!r}")
        rng = random.Random(seed)
        faults = []
        for t in range(int(n_ticks)):
            if rng.random() >= rate:
                continue
            kind = kinds[rng.randrange(len(kinds))]
            gi = rng.randrange(max(1, int(n_groups)))
            # source_timeout carries a group too: it targets ONE group's
            # worth of streams so healthy groups keep bit-identical inputs
            # (the reference shape: one exporter times out, not the whole
            # fleet) — live_loop maps group -> vector indices from its
            # routing (ChaosEngine.set_group_streams)
            targeted = kind in GROUP_KINDS or kind == "source_timeout"
            faults.append(Fault(
                kind=kind, tick=t,
                group=gi if targeted else None,
                seconds=0.05 if kind == "dispatch_hang" else 0.25,
            ))
        return cls(faults=faults, seed=seed)

    def to_dict(self) -> dict:
        # `magnitude` serializes only for the kind that reads it: every
        # pre-ISSUE-9 spec keeps its exact dict shape, so existing seeds'
        # digests stay pinned (tests/unit/test_replicate.py)
        faults = []
        for f in self.faults:
            d = asdict(f)
            if f.kind != "topology_burst":
                del d["magnitude"]
            faults.append(d)
        return {"seed": self.seed, "faults": faults}

    def shifted(self, base: int) -> "ChaosSpec":
        """The schedule as seen by a RESTARTED process that resumes at
        global tick `base`: faults before the resume point are dropped
        (they already fired — in particular a proc_exit that fired must
        not re-kill every restart), the rest shift to the restart's
        local tick clock. proc_exit fires AFTER its tick is journaled,
        so a restart's base is always past the killing fault's tick and
        the drop is unambiguous."""
        if base <= 0:
            return self
        out = []
        for f in self.faults:
            if f.tick + f.duration <= base:
                continue
            start = max(f.tick, base)
            out.append(Fault(
                kind=f.kind, tick=start - base,
                duration=f.tick + f.duration - start, group=f.group,
                streams=f.streams, seconds=f.seconds,
                ts_skew_s=f.ts_skew_s, magnitude=f.magnitude))
        return ChaosSpec(faults=out, seed=self.seed)

    def digest(self) -> str:
        """Stable content hash of the schedule — two runs with the same
        seed/spec must print the same digest (reproducibility proof in
        the chaos_soak artifact)."""
        blob = json.dumps(self.to_dict(), sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]


class ChaosEngine:
    """Injects a :class:`ChaosSpec` at the live loop's IO seams.

    The loop drives the tick clock (:meth:`set_tick`) and calls the
    ``on_dispatch`` / ``on_collect`` / ``on_checkpoint_save`` hooks at its
    seams; :meth:`wrap_source` and :meth:`wrap_alert_writer` wrap the
    objects whose faults live OUTSIDE the loop's code. Injections are
    logged in ``self.injected`` and counted per kind.
    """

    def __init__(self, spec: ChaosSpec):
        self.spec = spec
        self.tick = 0
        self.injected: list[dict] = []
        #: gi -> tuple of source-vector indices; filled by live_loop from
        #: its routing (set_group_streams) so a group-targeted
        #: source_timeout with streams=None hits exactly that group's
        #: slice of the vector — not the whole fleet
        self.group_streams: dict[int, tuple] = {}
        obs = get_registry()
        self._obs_injected = {
            kind: obs.counter(
                "rtap_obs_chaos_injected_total",
                "chaos faults actually injected, by kind", kind=kind)
            for kind in FAULT_KINDS
        }
        self._by_kind: dict[str, list[Fault]] = {}
        for f in spec.faults:
            self._by_kind.setdefault(f.kind, []).append(f)
        #: wire faults fire ONCE per scheduled Fault: the wire RETRIES
        #: the same record after a fault (reconnect + backfill, resync
        #: after a CRC skip), so a window that re-fired on the retry
        #: would be a permanent outage, not an injected fault
        self._wire_fired: set[int] = set()

    def set_tick(self, tick: int) -> None:
        """The loop's current tick — timestamps injections that happen
        outside a hook call (the alert-sink file wrapper)."""
        self.tick = int(tick)

    def set_group_streams(self, mapping: dict[int, tuple]) -> None:
        """Adopt the loop's group -> source-vector-indices routing (called
        at loop start and after every routing rebuild): generated
        source_timeout faults carry a target group, and only the loop
        knows which vector slice that group reads."""
        self.group_streams = {int(g): tuple(ix) for g, ix in mapping.items()}

    def _find(self, kind: str, tick: int,
              group: int | None = None) -> Fault | None:
        for f in self._by_kind.get(kind, ()):
            if f.active(tick, group):
                return f
        return None

    def _record(self, kind: str, tick: int, group: int | None = None) -> None:
        self._obs_injected[kind].inc()
        entry: dict = {"kind": kind, "tick": int(tick)}
        if group is not None:
            entry["group"] = int(group)
        self.injected.append(entry)

    # ---- loop seams -------------------------------------------------
    def on_dispatch(self, group: int, tick: int) -> None:
        """Called before a group's dispatch; may block (hang) or raise."""
        f = self._find("dispatch_hang", tick, group)
        if f is not None:
            self._record("dispatch_hang", tick, group)
            time.sleep(f.seconds)
        if self._find("dispatch_exception", tick, group) is not None:
            self._record("dispatch_exception", tick, group)
            raise ChaosError(
                f"chaos: dispatch exception (group {group}, tick {tick})")

    def on_collect(self, group: int, tick: int) -> None:
        """Called before a group's collect; may raise."""
        if self._find("collect_exception", tick, group) is not None:
            self._record("collect_exception", tick, group)
            raise ChaosError(
                f"chaos: collect exception (group {group}, tick {tick})")

    def on_checkpoint_save(self, group: int, tick: int) -> None:
        """Called before a group's checkpoint save; may raise OSError."""
        if self._find("checkpoint_oserror", tick, group) is not None:
            self._record("checkpoint_oserror", tick, group)
            raise OSError(28, "chaos: no space left on device")

    def on_wire(self, tick: int, data: bytes) -> bytes:
        """The byte-stream wire seam (ISSUE 8): consulted per shipped
        record by the replication sender (resilience/replicate.py) and
        by binary-ingest feeders that opt in. Keyed by the RECORD's
        tick, not the wall clock, so a seeded schedule is an exact
        reproducer. May block (``stall_socket`` — the leader's tick
        must not stall, which is the bounded-buffer property this
        proves), raise (``conn_drop`` — reconnect + journal backfill),
        or return corrupted bytes (``corrupt_bytes`` — the receiver's
        CRC walker must skip, never apply, and resync via its gap
        request)."""
        f = self._find("stall_socket", tick)
        if f is not None and id(f) not in self._wire_fired:
            self._wire_fired.add(id(f))
            self._record("stall_socket", tick)
            time.sleep(f.seconds)
        f = self._find("conn_drop", tick)
        if f is not None and id(f) not in self._wire_fired:
            self._wire_fired.add(id(f))
            self._record("conn_drop", tick)
            raise ConnectionResetError(
                f"chaos: wire connection dropped (tick {tick})")
        f = self._find("corrupt_bytes", tick)
        if f is not None and id(f) not in self._wire_fired:
            self._wire_fired.add(id(f))
            self._record("corrupt_bytes", tick)
            out = bytearray(data)
            if out:
                out[len(out) // 2] ^= 0xFF  # deterministic single flip
            return bytes(out)
        return data

    def on_tick_ingested(self, tick: int) -> None:
        """Called right after the tick's row was ingested (and journaled,
        when a journal is armed); a scheduled proc_exit dies HERE —
        abruptly, no cleanup, no flush (os._exit). Firing after the
        journal append makes the restart semantics unambiguous: the
        killing tick is on disk, the resumed process replays it, and
        ChaosSpec.shifted(base) drops the fault for good."""
        if self._find("proc_exit", tick) is not None:
            self._record("proc_exit", tick)
            import os

            os._exit(PROC_EXIT_CODE)

    # ---- object wrappers --------------------------------------------
    def wrap_source(self, source):
        """Wrap a live_loop source callable with the source fault kinds;
        delegates every other attribute (drain_unknown, set_ids, ...)."""
        return _ChaosSource(self, source)

    def wrap_alert_writer(self, writer) -> None:
        """Wrap the writer's underlying file so scripted windows raise
        OSError on write/flush — exercising AlertWriter's own
        retry-then-quarantine path from below, not around it."""
        writer.wrap_sink(lambda fh: _FaultyFile(fh, self))


class _ChaosSource:
    """Source-callable wrapper injecting the ``source_*`` fault kinds."""

    def __init__(self, engine: ChaosEngine, inner):
        self._engine = engine
        self._inner = inner

    def __call__(self, tick: int):
        eng = self._engine
        if eng._find("source_conn_drop", tick) is not None:
            eng._record("source_conn_drop", tick)
            raise ConnectionResetError("chaos: connection dropped")
        if eng._find("source_malformed", tick) is not None:
            eng._record("source_malformed", tick)
            raise ValueError("chaos: malformed payload")
        values, ts = self._inner(tick)
        f = eng._find("source_timeout", tick)
        if f is not None:
            eng._record("source_timeout", tick, f.group)
            values = np.array(values, np.float32, copy=True)
            streams = f.streams
            if streams is None and f.group is not None:
                # group-targeted fault without explicit indices: the
                # loop's routing says which slice the group reads
                streams = eng.group_streams.get(f.group)
            if streams is None:
                values[...] = np.nan
            else:
                values[list(streams)] = np.nan
        f = eng._find("source_backwards_ts", tick)
        if f is not None:
            eng._record("source_backwards_ts", tick)
            ts = int(ts) - int(f.ts_skew_s)
        f = eng._find("topology_burst", tick)
        if f is not None:
            # correlated multi-stream burst (ISSUE 9): flood the targeted
            # indices (None = the whole fleet — a global brown-out). NaNs
            # from an overlapping source_timeout stay NaN: a timed-out
            # exporter reports nothing, burst or not.
            eng._record("topology_burst", tick)
            values = np.array(values, np.float32, copy=True)
            if f.streams is None:
                values += np.float32(f.magnitude)
            else:
                values[list(f.streams)] += np.float32(f.magnitude)
        return values, ts

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _FaultyFile:
    """File-object proxy whose writes raise OSError during fault windows
    (the engine's tick clock decides). Everything else delegates."""

    def __init__(self, fh, engine: ChaosEngine):
        self._fh = fh
        self._engine = engine

    def _check(self) -> None:
        eng = self._engine
        if eng._find("alert_sink_oserror", eng.tick) is not None:
            eng._record("alert_sink_oserror", eng.tick)
            raise OSError(28, "chaos: no space left on device")

    def write(self, s):
        self._check()
        return self._fh.write(s)

    def writelines(self, lines):
        self._check()
        return self._fh.writelines(lines)

    def flush(self):
        self._check()
        return self._fh.flush()

    def close(self):
        return self._fh.close()

    def __getattr__(self, name):
        return getattr(self._fh, name)
