"""Retry + circuit-breaker policies for the serve stack's IO edges.

The reference keeps scoring healthy node-metric streams while the cluster
around it misbehaves (SURVEY.md §2.2 C18, §3.3): an exporter that times
out, an alert sink on a full disk, or a flapping TCP peer is THAT edge's
problem, never the loop's. These two policies are the shared mechanism:

- :class:`Retry` — bounded attempts with exponential backoff + jitter.
  The jitter stream is seeded (``random.Random(seed)``), so a scripted
  chaos run replays the exact same delay schedule — determinism is a
  feature of the whole resilience layer, not just the fault injector.
- :class:`CircuitBreaker` — per-endpoint closed/open/half-open gate.
  After ``fail_threshold`` consecutive failures the endpoint is skipped
  outright (no connect, no timeout wait) until ``cooldown_s`` passes;
  one half-open probe then decides re-close vs re-open. A dead exporter
  must cost the tick nothing after the breaker opens — the poll timeout
  alone (0.5 s default) would otherwise eat half the 1 s cadence budget
  every tick for the whole outage.

Both emit through ``rtap_tpu.obs`` (retry attempts, breaker transitions,
short-circuited calls) so an operator sees the policy working instead of
inferring it from latency shifts; docs/RESILIENCE.md is the runbook.
"""

from __future__ import annotations

import random
import time
from typing import Callable

from rtap_tpu.obs import get_registry

__all__ = ["CircuitBreaker", "CircuitOpenError", "Retry"]


class CircuitOpenError(RuntimeError):
    """Raised by :meth:`CircuitBreaker.call` while the breaker is open."""


class Retry:
    """Bounded retry with exponential backoff and seeded jitter.

    ``attempts`` counts TOTAL tries (1 = no retry). Delay before retry i
    (1-based) is ``min(base_delay_s * 2**(i-1), max_delay_s)`` plus a
    uniform jitter of up to ``jitter`` of that delay — jitter decorrelates
    a fleet of producers hammering a recovering endpoint in lockstep.
    The jitter PRNG is private and seeded: same seed, same schedule
    (chaos runs and tests depend on it; never use the global random).
    """

    def __init__(self, attempts: int = 3, base_delay_s: float = 0.05,
                 max_delay_s: float = 2.0, jitter: float = 0.1,
                 seed: int = 0, sleep: Callable[[float], None] = time.sleep,
                 op: str = "unnamed"):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1; got {attempts}")
        if base_delay_s < 0 or max_delay_s < base_delay_s:
            raise ValueError(
                f"need 0 <= base_delay_s <= max_delay_s; got "
                f"{base_delay_s}, {max_delay_s}")
        if not 0 <= jitter <= 1:
            raise ValueError(f"jitter must be in [0, 1]; got {jitter}")
        self.attempts = int(attempts)
        self.base_delay_s = float(base_delay_s)
        self.max_delay_s = float(max_delay_s)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self._sleep = sleep
        self.op = op
        self._obs_retries = get_registry().counter(
            "rtap_obs_retry_attempts_total",
            "retries performed after a failed attempt, by operation",
            op=op)

    def delay_for(self, attempt: int) -> float:
        """Backoff before retry `attempt` (1-based), jitter included."""
        d = min(self.base_delay_s * (2.0 ** (attempt - 1)), self.max_delay_s)
        return d * (1.0 + self.jitter * self._rng.random())

    def backoff(self, attempt: int) -> None:
        """Count + sleep the backoff before retry `attempt` — for manual
        retry loops that can't funnel through :meth:`call` (send_jsonl
        tracks partially-delivered batches across attempts)."""
        self._obs_retries.inc()
        self._sleep(self.delay_for(attempt))

    def call(self, fn: Callable, *args,
             retry_on: tuple = (OSError,), **kwargs):
        """Run ``fn`` with up to ``attempts`` tries; re-raises the last
        failure once the budget is exhausted. Only exceptions matching
        ``retry_on`` are retried — anything else propagates immediately
        (a programming error must not be retried into the noise)."""
        for attempt in range(1, self.attempts + 1):
            try:
                return fn(*args, **kwargs)
            except retry_on:
                if attempt == self.attempts:
                    raise
                self.backoff(attempt)


class CircuitBreaker:
    """Per-endpoint closed → open → half-open gate over an IO call.

    States: **closed** (calls flow; ``fail_threshold`` CONSECUTIVE
    failures open it), **open** (calls are short-circuited — `allow()`
    is False — until ``cooldown_s`` of wall clock passes), **half-open**
    (exactly one probe call is allowed; success re-closes, failure
    re-opens and restarts the cooldown). The caller drives it through
    either :meth:`call` (raises :class:`CircuitOpenError` when open) or
    the `allow`/`record_success`/`record_failure` triplet when it wants
    to substitute a degraded result (a NaN tick) instead of raising.
    """

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, fail_threshold: int = 5, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "unnamed"):
        if fail_threshold < 1:
            raise ValueError(
                f"fail_threshold must be >= 1; got {fail_threshold}")
        if cooldown_s <= 0:
            raise ValueError(f"cooldown_s must be > 0; got {cooldown_s}")
        self.fail_threshold = int(fail_threshold)
        self.cooldown_s = float(cooldown_s)
        self.name = name
        self._clock = clock
        self.state = self.CLOSED
        self.consecutive_failures = 0
        self._opened_at = 0.0
        obs = get_registry()
        self._obs_transitions = {
            s: obs.counter(
                "rtap_obs_breaker_transitions_total",
                "circuit-breaker state entries by (breaker, state)",
                breaker=name, state=s)
            for s in (self.OPEN, self.HALF_OPEN, self.CLOSED)
        }
        self._obs_short = obs.counter(
            "rtap_obs_breaker_short_circuits_total",
            "calls skipped because the breaker was open", breaker=name)

    def _transition(self, state: str) -> None:
        if state != self.state:
            self.state = state
            self._obs_transitions[state].inc()

    def allow(self) -> bool:
        """True if a call may proceed now. An open breaker past its
        cooldown moves to half-open and admits ONE probe; the probe's
        record_success/record_failure decides what happens next."""
        if self.state == self.CLOSED:
            return True
        if self.state == self.OPEN:
            if self._clock() - self._opened_at >= self.cooldown_s:
                self._transition(self.HALF_OPEN)
                return True
            self._obs_short.inc()
            return False
        # half-open: the single probe is already in flight this tick —
        # further calls wait for its verdict
        self._obs_short.inc()
        return False

    def record_success(self) -> None:
        self.consecutive_failures = 0
        self._transition(self.CLOSED)

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        if self.state == self.HALF_OPEN \
                or self.consecutive_failures >= self.fail_threshold:
            self._opened_at = self._clock()
            self._transition(self.OPEN)

    def call(self, fn: Callable, *args, **kwargs):
        """Gate ``fn`` through the breaker; raises CircuitOpenError when
        the breaker refuses the call (callers needing a degraded value
        instead use allow()/record_* directly)."""
        if not self.allow():
            raise CircuitOpenError(
                f"circuit breaker {self.name!r} is open "
                f"({self.consecutive_failures} consecutive failures)")
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out
