"""Graceful degradation: shed load in declared steps under sustained misses.

The 1 s cadence is a real-time contract, and the watchdog already NAMES the
failure (missed_tick events); this controller REACTS to it. When deadline
misses persist, the loop sheds load down a declared ladder instead of
missing every deadline at full quality:

    level 0  normal           — full-rate learning, declared cadence
    level 1  learn_thin       — learn only every ``thin_factor``-th tick
                                (the SCALING.md learning-cadence lever,
                                applied at dispatch time: same compiled
                                programs, the learn flag is already a
                                traced variant)
    level 2  score_only       — freeze learning entirely (~85% of the
                                fused step on silicon); scores and alerts
                                still flow, likelihood keeps adapting
    level 3  tick_widen       — widen the effective cadence by
                                ``widen_factor`` (score every sample we
                                can, admit the contract changed — and say
                                so on the alert stream)

Hysteresis keeps the ladder from flapping: escalate after ``degrade_after``
misses inside a sliding window of ``window`` ticks, de-escalate one level
only after ``recover_after`` CONSECUTIVE clean ticks. Every transition
emits a structured ``degraded``/``recovered`` event (alert JSONL stream)
and moves the ``rtap_obs_degradation_level`` gauge, so a scraper sees the
ladder position and the alert file says when and why it moved.
"""

from __future__ import annotations

from collections import deque
from typing import Callable

from rtap_tpu.obs import get_registry

__all__ = ["DegradationController", "LADDER"]

#: the declared ladder, in escalation order (level = index + 1)
LADDER = ("learn_thin", "score_only", "tick_widen")


class DegradationController:
    """Hysteresis state machine from per-tick miss facts to a shed level.

    Drive it with :meth:`observe` once per tick; read the effects through
    :meth:`learn_allowed` and :meth:`cadence_scale`. ``event_sink`` is any
    JSON-able-dict callable (the loop passes ``AlertWriter.emit_event``).
    """

    def __init__(self, window: int = 10, degrade_after: int = 3,
                 recover_after: int = 15, thin_factor: int = 4,
                 widen_factor: float = 2.0,
                 event_sink: Callable[[dict], None] | None = None):
        if window < 1 or degrade_after < 1 or recover_after < 1:
            raise ValueError(
                "window, degrade_after, recover_after must all be >= 1; got "
                f"{window}, {degrade_after}, {recover_after}")
        if degrade_after > window:
            raise ValueError(
                f"degrade_after ({degrade_after}) can never trigger inside a "
                f"window of {window} ticks")
        if thin_factor < 2:
            raise ValueError(f"thin_factor must be >= 2; got {thin_factor}")
        if widen_factor <= 1.0:
            raise ValueError(f"widen_factor must be > 1; got {widen_factor}")
        self.window = int(window)
        self.degrade_after = int(degrade_after)
        self.recover_after = int(recover_after)
        self.thin_factor = int(thin_factor)
        self.widen_factor = float(widen_factor)
        #: event sink (JSON-able-dict callable); live_loop fills it with
        #: AlertWriter.emit_event when the caller left it None
        self.sink = event_sink
        self.level = 0
        self.max_level_seen = 0
        self.transitions = 0
        self._recent = deque(maxlen=self.window)  # sliding miss window
        self._clean_run = 0
        obs = get_registry()
        self._obs_level = obs.gauge(
            "rtap_obs_degradation_level",
            "current load-shedding ladder position (0 = normal; "
            "1 learn_thin, 2 score_only, 3 tick_widen)")
        self._obs_level.set(0)
        self._obs_events = {
            kind: obs.counter(
                "rtap_obs_resilience_events_total",
                "structured resilience events by kind", event=kind)
            for kind in ("degraded", "recovered")
        }

    def _emit(self, kind: str, tick: int, **fields) -> None:
        self._obs_events[kind].inc()
        if self.sink is not None:
            self.sink({"event": kind, "tick": int(tick), **fields})

    def _step_name(self, level: int) -> str:
        return "normal" if level == 0 else LADDER[level - 1]

    def observe(self, tick: int, missed: bool) -> int:
        """One tick's deadline verdict; returns the (possibly new) level.

        Escalation clears the miss window (the NEW level gets a fresh
        window to prove itself — without this, one bad burst would ride
        the ladder to the bottom in consecutive ticks regardless of
        whether shedding helped). Recovery is one level at a time.
        """
        self._recent.append(bool(missed))
        if missed:
            self._clean_run = 0
            if sum(self._recent) >= self.degrade_after \
                    and self.level < len(LADDER):
                self.level += 1
                self.max_level_seen = max(self.max_level_seen, self.level)
                self.transitions += 1
                self._recent.clear()
                self._obs_level.set(self.level)
                self._emit("degraded", tick, level=self.level,
                           step=self._step_name(self.level))
        else:
            self._clean_run += 1
            if self.level > 0 and self._clean_run >= self.recover_after:
                self.level -= 1
                self.transitions += 1
                self._clean_run = 0
                self._obs_level.set(self.level)
                self._emit("recovered", tick, level=self.level,
                           step=self._step_name(self.level))
        return self.level

    def learn_allowed(self, tick: int) -> bool:
        """Whether the loop may dispatch this tick's chunk with learning.

        Level 1 thins to every ``thin_factor``-th tick; level >= 2 freezes
        learning entirely. (Composes with the caller's own ``learn`` flag —
        the controller only ever REMOVES learning, never adds it.)"""
        if self.level == 0:
            return True
        if self.level == 1:
            return tick % self.thin_factor == 0
        return False

    @property
    def cadence_scale(self) -> float:
        """Multiplier on the declared cadence (level 3 widens the tick)."""
        return self.widen_factor if self.level >= 3 else 1.0

    def stats(self) -> dict:
        return {"level": self.level, "max_level": self.max_level_seen,
                "transitions": self.transitions}
