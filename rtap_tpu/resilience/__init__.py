"""rtap_tpu.resilience — fault policies, graceful degradation, chaos.

The service layer's answer to the watchdog's observations (rtap_tpu.obs
detects; this package reacts): :class:`Retry` and :class:`CircuitBreaker`
wrap the IO edges (HTTP polls, JSONL producers, the alert sink,
checkpoint saves — policies.py), a :class:`DegradationController` sheds
load down a declared ladder under sustained deadline misses (degrade.py),
and a deterministic seedable :class:`ChaosEngine` injects scripted faults
at the loop's seams so every recovery path is exercised in tier-1 rather
than trusted (chaos.py; ``scripts/chaos_soak.py``, ``serve
--chaos-spec``). The durability layer (ISSUE 5) lives here too: a
:class:`TickJournal` write-ahead log of ingested tick rows with
torn-write-tolerant recovery (journal.py, ``serve --journal-dir``) and a
:class:`Supervisor` that restarts a dead serve child with backoff and a
budget (supervisor.py, ``serve --supervise``;
``scripts/crash_soak.py`` is the kill-9 acceptance soak). Availability
(ISSUE 8) lives in replicate.py: journal shipping to a hot standby,
a file lease with a monotonic fencing epoch, and promotion with an
exactly-once alert-stream splice (``serve --replicate-to`` /
``serve --standby``; ``scripts/failover_soak.py`` is the kill-9
failover acceptance soak). Group
quarantine itself lives in service/loop.py — it is
loop scheduling — but emits the resilience event vocabulary documented in
docs/RESILIENCE.md.
"""

from rtap_tpu.resilience.chaos import (
    FAULT_KINDS,
    GENERATED_KINDS,
    PROC_EXIT_CODE,
    ChaosEngine,
    ChaosError,
    ChaosSpec,
    Fault,
)
from rtap_tpu.resilience.degrade import LADDER, DegradationController
from rtap_tpu.resilience.journal import (
    TickJournal,
    count_journal_ticks,
    last_journal_tick,
    parse_fsync,
)
from rtap_tpu.resilience.policies import CircuitBreaker, CircuitOpenError, Retry
from rtap_tpu.resilience.replicate import (
    FENCED_RC,
    Lease,
    ReplicationSender,
    StandbyFollower,
)
from rtap_tpu.resilience.supervisor import Supervisor, strip_supervise_flags

__all__ = [
    "FAULT_KINDS",
    "FENCED_RC",
    "GENERATED_KINDS",
    "LADDER",
    "PROC_EXIT_CODE",
    "ChaosEngine",
    "ChaosError",
    "ChaosSpec",
    "CircuitBreaker",
    "CircuitOpenError",
    "DegradationController",
    "Fault",
    "Lease",
    "ReplicationSender",
    "Retry",
    "StandbyFollower",
    "Supervisor",
    "TickJournal",
    "count_journal_ticks",
    "last_journal_tick",
    "parse_fsync",
    "strip_supervise_flags",
]
