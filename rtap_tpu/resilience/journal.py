"""Crash-consistent tick journal: a per-tick write-ahead log for serve.

HTM temporal-memory state is sequential — every tick lost at a crash is
temporal context the model cannot recover (PAPERS.md, SDR sequence
properties). Checkpoints bound the loss to a save round; the journal
closes the remaining gap: every ingested tick row (raw values + source
timestamp) is appended to a bounded, segment-rotated, CRC-per-record
append-only log BEFORE it is scored, so a restarted serve can restore
the newest checkpoint and then replay the journaled ticks past the
checkpoint's tick cursor through the normal scoring path — reaching the
crash point bit-identically to an uninterrupted run (service/loop.py
owns the replay; this module owns the format and its recovery).

Durability model
----------------
Every append is ``flush()``-ed to the kernel, so a SIGKILL (the crash
soak's fault) loses at most the record being written at that instant.
Machine crashes / power loss are governed by the fsync policy:

- ``os``         — never fsync; the OS page cache decides (default)
- ``every-tick`` — fsync after every tick record (max durability)
- ``every-N``    — fsync once per N tick records (middle ground)

Recovery tolerates torn writes: a corrupt or truncated segment tail is
truncated back to the last valid record — counted and surfaced, never a
refusal to start. Corruption in the middle of the log (bitrot) truncates
at the first bad record and drops the later segments; ticks recovered
are always a clean prefix.

Record framing (little-endian)::

    b"RJ" | type u8 | payload_len u32 | payload | crc32 u32

crc32 covers type + payload_len + payload. Record types:

- TICK   (1): tick i64, ts i64, ndim u8, dims i32*, float32 values
- CURSOR (2): tick i64, alert-sink byte offset i64 — the alert-delivery
  cursor, appended after each emitted chunk (diagnostic trail; the
  load-bearing alert cursor for exactly-once resume lives in the
  checkpoint meta, written at a fully-drained instant — see
  service/checkpoint.py and docs/RESILIENCE.md)
- FRAME  (3): tick i64, ts i64, width i32, raw RB1 ingest frame bytes
  (ISSUE 7): the binary ingest path journals the tick's wire frames
  VERBATIM instead of re-encoding the full-width value vector — a
  100k-stream fleet with 1k rows/tick writes ~10 KB instead of 400 KB.
  Replay decodes them through the registry's dispatch table
  (rtap_tpu/ingest/dispatch.decode_frames_to_row), which is
  valid because membership changes force a checkpoint + compaction
  boundary, so every frame in the replayable window was ingested under
  the membership the checkpoints resume.

Segments rotate at ``segment_bytes`` and are bounded by ``max_segments``
(oldest dropped + counted — sized so it never fires while checkpoints
are compacting normally). ``compact(upto_tick)`` drops segments whose
records all predate the latest checkpoint; service/loop.py calls it
after every successful save round, which keeps the journal's size
O(checkpoint_every) ticks.
"""

from __future__ import annotations

import os
import struct
import zlib
from pathlib import Path

import numpy as np

from rtap_tpu.obs import get_registry

__all__ = ["TickJournal", "JournaledFrames", "parse_fsync",
           "count_journal_ticks", "last_journal_tick", "first_journal_tick",
           "iter_raw_records", "FSYNC_POLICIES"]


class JournaledFrames:
    """A FRAME record's payload: the raw RB1 wire frames of one tick
    plus the dispatch width they were ingested at. The loop's journal
    replay materializes the value vector through the binary source's
    dispatch table (the codes are meaningless without it)."""

    __slots__ = ("width", "blob")

    def __init__(self, width: int, blob: bytes):
        self.width = int(width)
        self.blob = blob

_MAGIC = b"RJ"
_TICK = 1
_CURSOR = 2
_FRAME = 3
_TYPES = (_TICK, _CURSOR, _FRAME)
_HEADER = struct.Struct("<2sBI")  # magic, type, payload length
_CRC = struct.Struct("<I")
_TICK_HEAD = struct.Struct("<qqB")  # tick, ts, ndim
_DIM = struct.Struct("<i")
_CURSOR_PAYLOAD = struct.Struct("<qq")  # tick, alert-sink byte offset
_FRAME_HEAD = struct.Struct("<qqi")  # tick, ts, dispatch width
#: a payload larger than this is treated as frame corruption, not a
#: record (a flipped length byte must not make recovery try to allocate
#: gigabytes): 256 MiB comfortably exceeds any real fleet's tick row
_MAX_PAYLOAD = 256 << 20

FSYNC_POLICIES = ("os", "every-tick", "every-n")


def parse_fsync(spec: str) -> tuple[str, int]:
    """Parse the operator-facing fsync policy string: ``os``,
    ``every-tick``, or ``every-<N>`` (fsync once per N tick records).
    Returns (policy, n); raises ValueError on anything else."""
    spec = str(spec).strip().lower()
    if spec == "os":
        return "os", 0
    if spec == "every-tick":
        return "every-tick", 0
    if spec.startswith("every-"):
        try:
            n = int(spec[len("every-"):])
        except ValueError:
            n = 0
        if n >= 1:
            return "every-n", n
    raise ValueError(
        f"journal fsync policy must be 'os', 'every-tick', or 'every-<N>' "
        f"(N >= 1); got {spec!r}")


def _seg_name(seq: int) -> str:
    return f"seg-{seq:08d}.rjl"


def _list_segments(path: Path) -> list[Path]:
    try:
        names = sorted(
            n for n in os.listdir(path)
            if n.startswith("seg-") and n.endswith(".rjl"))
    except OSError:
        return []
    return [path / n for n in names]


def _walk_headers(path: Path):
    """Yield (type, payload_len, file_handle) per structurally valid
    record across a journal dir's segments — headers only: payloads are
    seeked over, CRCs skipped, a torn tail ends the walk. The handle is
    positioned at the payload start; consumers may read a prefix (the
    walk reseeks to the record end regardless). The single framing
    scanner behind the cheap probes below (full CRC-checked parsing
    lives in TickJournal._recover)."""
    for seg in _list_segments(path):
        try:
            with open(seg, "rb") as f:
                size = os.fstat(f.fileno()).st_size
                off = 0
                while off + _HEADER.size <= size:
                    head = f.read(_HEADER.size)
                    if len(head) < _HEADER.size:
                        break
                    magic, typ, ln = _HEADER.unpack(head)
                    end = off + _HEADER.size + ln + _CRC.size
                    if magic != _MAGIC or typ not in _TYPES \
                            or ln > _MAX_PAYLOAD or end > size:
                        break
                    yield typ, ln, f
                    f.seek(end)
                    off = end
        except OSError:
            break


def count_journal_ticks(path: str | Path) -> int:
    """Cheap header-walk count of valid tick-carrying records (TICK and
    FRAME) in a journal dir. NOTE: checkpoint compaction deletes whole
    segments, so this number can SHRINK across a run — use
    :func:`last_journal_tick` for monotonic progress probing."""
    return sum(1 for typ, _ln, _f in _walk_headers(Path(path))
               if typ in (_TICK, _FRAME))


def last_journal_tick(path: str | Path) -> int:
    """Highest tick index visible in a journal dir (TICK or FRAME
    records; header walk, CRCs skipped, torn tail ends the scan) — the
    crash soak's progress probe. Unlike a record COUNT this is
    monotonic across segment rotation AND checkpoint compaction; -1
    for an empty/missing journal."""
    last = -1
    for typ, ln, f in _walk_headers(Path(path)):
        if typ in (_TICK, _FRAME) and ln >= 8:
            (tick,) = struct.unpack("<q", f.read(8))
            last = max(last, int(tick))
    return last


def first_journal_tick(path: str | Path) -> int:
    """Lowest tick-carrying record index still on disk (header walk) —
    the replication sender's backfill probe: a standby asking for ticks
    below this cannot be served from the journal and falls back to the
    full-checkpoint fetch (resilience/replicate.py). -1 when empty."""
    for typ, ln, f in _walk_headers(Path(path)):
        if typ in (_TICK, _FRAME) and ln >= 8:
            (tick,) = struct.unpack("<q", f.read(8))
            return int(tick)
    return -1


def iter_raw_records(path: str | Path, from_tick: int = 0):
    """Yield ``(typ, tick, record_bytes)`` per CRC-valid record on disk
    whose tick is >= ``from_tick`` (CURSOR records ride along at their
    tick), in journal order. This is the replication sender's disk
    backfill: a reconnecting standby is caught up from the journal
    itself — the bytes yielded are the exact framed records an online
    tee would have shipped. A structural/CRC fault (bitrot, a segment
    unlinked mid-read by compaction, the torn tail) skips the REST of
    that segment and continues with the next — the receiver sees the
    missing ticks as a gap, and its no-progress resync escalates to the
    checkpoint fallback (a mid-journal fault must never turn backfill
    into a livelock)."""
    for seg in _list_segments(Path(path)):
        try:
            data = seg.read_bytes()
        except OSError:
            continue
        off = 0
        while off + _HEADER.size + _CRC.size <= len(data):
            magic, typ, ln = _HEADER.unpack_from(data, off)
            end = off + _HEADER.size + ln + _CRC.size
            if magic != _MAGIC or typ not in _TYPES \
                    or ln > _MAX_PAYLOAD or end > len(data):
                break
            payload = data[off + _HEADER.size:end - _CRC.size]
            (crc,) = _CRC.unpack_from(data, end - _CRC.size)
            if crc != zlib.crc32(data[off + 2:off + _HEADER.size] + payload):
                break
            if len(payload) >= 8:
                (tick,) = struct.unpack_from("<q", payload, 0)
                if tick >= from_tick:
                    yield typ, int(tick), data[off:end]
            off = end


class TickJournal:
    """Append-only per-tick WAL with torn-write-tolerant recovery.

    Construction performs recovery: existing segments are scanned in
    order, the torn/corrupt tail (if any) is truncated back to the last
    valid record, and the surviving tick rows land in
    ``self.recovered_ticks`` (list of ``(tick, ts, values)``) for the
    loop to replay. Appends then continue the same log — global tick
    indices are monotonic across process restarts.
    """

    def __init__(self, path: str | Path, *, segment_bytes: int = 4 << 20,
                 fsync: str = "os", fsync_every: int = 64,
                 max_segments: int = 256):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync must be one of {FSYNC_POLICIES}; got {fsync!r} "
                "(parse_fsync handles the operator string forms)")
        if fsync == "every-n" and fsync_every < 1:
            raise ValueError(
                f"fsync_every must be >= 1 with every-n; got {fsync_every}")
        if segment_bytes < 1024:
            raise ValueError(
                f"segment_bytes must be >= 1024; got {segment_bytes}")
        if max_segments < 2:
            raise ValueError(f"max_segments must be >= 2; got {max_segments}")
        self.path = Path(path).absolute()
        self.path.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = int(segment_bytes)
        self.max_segments = int(max_segments)
        self.fsync = fsync
        self.fsync_every = int(fsync_every)
        #: recovered state (filled by the scan below)
        self.recovered_ticks: list[tuple[int, int, np.ndarray]] = []
        self.cursors: list[tuple[int, int]] = []
        self.truncations = 0  # torn/corrupt tails truncated
        self.truncated_bytes = 0
        self.dropped_segments = 0  # segments after a mid-log corruption
        self.duplicate_ticks_skipped = 0
        # append accounting
        self.appended_ticks = 0
        self.appended_cursors = 0
        self.appended_bytes = 0
        self.fsyncs = 0
        self.rotations = 0
        self.evicted_segments = 0  # max_segments bound fired (data loss)
        self._ticks_since_fsync = 0
        self._fh = None
        self._seg_size = 0
        self._seg_seq = 0
        #: replication tee (resilience/replicate.py, ISSUE 8): when set,
        #: called with (typ, tick, record_bytes) AFTER each record is
        #: flushed to the kernel — the exact framed bytes, so a standby
        #: applying them rebuilds a byte-identical journal. The tee must
        #: never block (the sender buffers bounded, drop-oldest).
        self.tee = None
        #: replication compaction floor: when set, compact(upto) is
        #: clamped to min(upto, compact_floor()) so the leader never
        #: drops records a connected standby has not acked past (the
        #: PR 5 pause-while-quarantined rule, applied to replication).
        #: Returning None means no clamp (no standby connected).
        self.compact_floor = None
        #: per-segment max record tick, for compact() (name -> tick)
        self._seg_max_tick: dict[str, int] = {}
        obs = get_registry()
        self._obs_appends = obs.counter(
            "rtap_obs_journal_appends_total",
            "journal records appended (tick rows + alert cursors)")
        self._obs_bytes = obs.counter(
            "rtap_obs_journal_bytes_total",
            "bytes appended to the tick journal")
        self._obs_fsyncs = obs.counter(
            "rtap_obs_journal_fsyncs_total",
            "explicit fsyncs issued by the journal's durability policy")
        self._obs_rotations = obs.counter(
            "rtap_obs_journal_segments_rotated_total",
            "journal segment rotations (segment_bytes reached)")
        self._obs_truncated = obs.counter(
            "rtap_obs_journal_truncations_total",
            "torn/corrupt journal tails truncated back to the last valid "
            "record during recovery (never a refusal to start)")
        self._obs_compacted = obs.counter(
            "rtap_obs_journal_compacted_segments_total",
            "journal segments dropped by checkpoint-driven compaction")
        self._obs_segments = obs.gauge(
            "rtap_obs_journal_segments", "journal segments currently on disk")
        self._obs_append_seconds = obs.histogram(
            "rtap_obs_journal_append_seconds",
            "wall seconds per journal tick append (format + write + flush "
            "+ policy fsync)")
        self._recover()
        self.recovered_count = len(self.recovered_ticks)
        self.next_tick = (self.recovered_ticks[-1][0] + 1
                          if self.recovered_ticks else 0)
        self._obs_segments.set(len(_list_segments(self.path)))

    def release_recovered(self) -> None:
        """Drop the materialized recovery rows once the caller has
        replayed them — a large replay window (up to max_segments *
        segment_bytes of decoded arrays) must not stay resident for the
        rest of the process. Counts survive in stats()."""
        self.recovered_ticks = []
        self.cursors = []

    # ---- recovery ----------------------------------------------------
    def _recover(self) -> None:
        segs = _list_segments(self.path)
        corrupt = False
        last_tick = -1
        for seg in segs:
            seq = int(seg.name[4:-4])
            self._seg_seq = max(self._seg_seq, seq)
            if corrupt:
                # everything after the first corruption is dropped: the
                # replayable log must be a contiguous prefix of ticks
                try:
                    size = seg.stat().st_size
                    seg.unlink()
                except OSError:
                    size = 0
                self.dropped_segments += 1
                self.truncated_bytes += size
                continue
            try:
                data = seg.read_bytes()
            except OSError:
                corrupt = True
                self.truncations += 1
                self._obs_truncated.inc()
                continue
            off = 0
            seg_max = -1
            while off + _HEADER.size + _CRC.size <= len(data):
                magic, typ, ln = _HEADER.unpack_from(data, off)
                end = off + _HEADER.size + ln + _CRC.size
                if magic != _MAGIC or typ not in _TYPES \
                        or ln > _MAX_PAYLOAD or end > len(data):
                    break
                payload = data[off + _HEADER.size:end - _CRC.size]
                (crc,) = _CRC.unpack_from(data, end - _CRC.size)
                if crc != zlib.crc32(data[off + 2:off + _HEADER.size]
                                     + payload):
                    break
                rec = self._parse(typ, payload)
                if rec is None:
                    break
                if typ in (_TICK, _FRAME):
                    if rec[0] <= last_tick:
                        # out-of-order / repeated index: keep the FIRST
                        # copy (appends never reuse an index — the
                        # loop's journal_base is floored at next_tick —
                        # so a duplicate only arises from hand-edited or
                        # stitched journals; first-wins keeps the scan
                        # deterministic)
                        self.duplicate_ticks_skipped += 1
                    else:
                        self.recovered_ticks.append(rec)
                        last_tick = rec[0]
                    seg_max = max(seg_max, rec[0])
                else:
                    self.cursors.append(rec)
                    seg_max = max(seg_max, rec[0])
                off = end
            if off < len(data):
                # torn or corrupt tail: truncate back to the last valid
                # record; if this is NOT the last segment, later segments
                # are dropped above (corrupt stays set)
                try:
                    with open(seg, "r+b") as f:
                        f.truncate(off)
                except OSError:
                    pass
                self.truncations += 1
                self.truncated_bytes += len(data) - off
                self._obs_truncated.inc()
                corrupt = True
            if seg_max >= 0:
                self._seg_max_tick[seg.name] = seg_max

    @staticmethod
    def _parse(typ: int, payload: bytes):
        try:
            if typ == _CURSOR:
                tick, offset = _CURSOR_PAYLOAD.unpack(payload)
                return int(tick), int(offset)
            if typ == _FRAME:
                tick, ts, width = _FRAME_HEAD.unpack_from(payload, 0)
                if width < 0:
                    return None
                return int(tick), int(ts), JournaledFrames(
                    int(width), payload[_FRAME_HEAD.size:])
            tick, ts, ndim = _TICK_HEAD.unpack_from(payload, 0)
            off = _TICK_HEAD.size
            shape = []
            for _ in range(ndim):
                (d,) = _DIM.unpack_from(payload, off)
                off += _DIM.size
                shape.append(int(d))
            n = int(np.prod(shape)) if shape else 1
            raw = payload[off:off + 4 * n]
            if len(raw) != 4 * n or any(d < 0 for d in shape):
                return None
            values = np.frombuffer(raw, np.float32).reshape(shape).copy()
            return int(tick), int(ts), values
        except (struct.error, ValueError):
            return None

    # ---- append ------------------------------------------------------
    def _open_segment(self) -> None:
        if self._fh is not None:
            return
        segs = _list_segments(self.path)
        if segs and segs[-1].stat().st_size < self.segment_bytes:
            seg = segs[-1]
        else:
            self._seg_seq += 1
            seg = self.path / _seg_name(self._seg_seq)
        self._fh = open(seg, "ab")
        self._seg_name = seg.name
        self._seg_size = seg.stat().st_size

    def _rotate(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        self._seg_seq += 1
        seg = self.path / _seg_name(self._seg_seq)
        self._fh = open(seg, "ab")
        self._seg_name = seg.name
        self._seg_size = 0
        self.rotations += 1
        self._obs_rotations.inc()
        segs = _list_segments(self.path)
        while len(segs) > self.max_segments:
            # hard bound: oldest segment evicted (counted — this is data
            # loss past the bound; size max_segments so checkpoints
            # compact long before it fires)
            victim = segs.pop(0)
            try:
                victim.unlink()
            except OSError:
                break
            self._seg_max_tick.pop(victim.name, None)
            self.evicted_segments += 1
        self._obs_segments.set(len(segs))

    def _append(self, typ: int, payload: bytes, tick: int) -> None:
        self._open_segment()
        if self._seg_size and self._seg_size + len(payload) + 16 \
                > self.segment_bytes:
            self._rotate()
        head = _HEADER.pack(_MAGIC, typ, len(payload))
        rec = head + payload + _CRC.pack(zlib.crc32(head[2:] + payload))
        self._fh.write(rec)
        # flush to the kernel unconditionally: a SIGKILL after this point
        # loses nothing (fsync below is for power loss, per policy)
        self._fh.flush()
        if self.tee is not None:
            # ship AFTER the local flush: the standby can never be ahead
            # of the leader's own durable log
            self.tee(typ, int(tick), rec)
        self._seg_size += len(rec)
        self._seg_max_tick[self._seg_name] = max(
            self._seg_max_tick.get(self._seg_name, -1), tick)
        self.appended_bytes += len(rec)
        self._obs_appends.inc()
        self._obs_bytes.inc(len(rec))

    def _append_tick_record(self, typ: int, tick: int, payload: bytes,
                            t0: float) -> None:
        """Shared tail of every tick-carrying append: write, advance
        the tick cursor, run the fsync policy, observe the cost — TICK
        and FRAME records must never diverge in durability semantics.
        ``t0`` is taken BEFORE the caller builds its payload, so the
        append histogram keeps covering format + write + flush + fsync
        (the pre-FRAME measurement contract)."""
        import time as _time

        self._append(typ, payload, int(tick))
        self.appended_ticks += 1
        self.next_tick = max(self.next_tick, int(tick) + 1)
        if self.fsync == "every-tick":
            self._fsync()
        elif self.fsync == "every-n":
            self._ticks_since_fsync += 1
            if self._ticks_since_fsync >= self.fsync_every:
                self._fsync()
        self._obs_append_seconds.observe(_time.perf_counter() - t0)

    def append_tick(self, tick: int, ts: int, values: np.ndarray) -> None:
        """Append one ingested tick row (the write-ahead record): global
        tick index, source timestamp, and the raw value vector in
        dispatch/routing order."""
        import time as _time

        t0 = _time.perf_counter()
        values = np.ascontiguousarray(values, np.float32)
        payload = (_TICK_HEAD.pack(int(tick), int(ts), values.ndim)
                   + b"".join(_DIM.pack(d) for d in values.shape)
                   + values.tobytes())
        self._append_tick_record(_TICK, tick, payload, t0)

    def append_tick_frames(self, tick: int, ts: int, width: int,
                           frames) -> None:
        """Append one ingested tick as its RAW binary ingest frames
        (ISSUE 7): the wire bytes land verbatim — no full-width
        re-encode — plus the dispatch width replay validates against.
        An empty frame list is a legal all-NaN tick (no data arrived)."""
        import time as _time

        t0 = _time.perf_counter()
        payload = (_FRAME_HEAD.pack(int(tick), int(ts), int(width))
                   + b"".join(frames))
        self._append_tick_record(_FRAME, tick, payload, t0)

    def append_cursor(self, tick: int, alerts_offset: int) -> None:
        """Append an alert-delivery cursor: alerts through global `tick`
        have been handed to the sink, whose byte offset is
        `alerts_offset` (diagnostic trail; see module docstring)."""
        self._append(_CURSOR,
                     _CURSOR_PAYLOAD.pack(int(tick), int(alerts_offset)),
                     int(tick))
        self.appended_cursors += 1

    def _fsync(self) -> None:
        try:
            os.fsync(self._fh.fileno())
        except OSError:
            return
        self.fsyncs += 1
        self._ticks_since_fsync = 0
        self._obs_fsyncs.inc()

    # ---- maintenance -------------------------------------------------
    def compact(self, upto_tick: int) -> int:
        """Drop whole segments whose records all predate `upto_tick`
        (the newest checkpoint's tick cursor): those ticks can never be
        replayed again. Returns segments dropped.

        With a replication ``compact_floor`` armed, the cut is clamped
        to what the standby has acked: a lagging-but-connected standby
        PAUSES compaction past its position (mirroring the PR 5
        quarantine pause) so the records it still needs stay on disk; a
        DISCONNECTED standby releases the clamp (bounded disk growth),
        and on reconnect past the gap it takes the full-checkpoint
        fallback instead (resilience/replicate.py)."""
        if self.compact_floor is not None:
            floor = self.compact_floor()
            if floor is not None:
                upto_tick = min(int(upto_tick), int(floor))
        dropped = 0
        for seg in _list_segments(self.path):
            if seg.name == getattr(self, "_seg_name", None) \
                    and self._fh is not None:
                continue  # never unlink the open segment
            if self._seg_max_tick.get(seg.name, upto_tick) >= upto_tick:
                continue
            try:
                seg.unlink()
            except OSError:
                continue
            self._seg_max_tick.pop(seg.name, None)
            dropped += 1
        if dropped:
            self._obs_compacted.inc(dropped)
            self._obs_segments.set(len(_list_segments(self.path)))
        return dropped

    def wipe(self) -> None:
        """Drop every segment and all recovered state (ISSUE 8): a hot
        standby adopting the leader's checkpoints discards a local
        mirror tail that extends past them — after a failover those
        records belong to the PRE-failover timeline, and the live
        leader's stream is the only authoritative continuation. The
        mirror re-syncs from the stream (disk backfill)."""
        self.close()
        for seg in _list_segments(self.path):
            try:
                seg.unlink()
            except OSError:
                pass
        self.recovered_ticks = []
        self.cursors = []
        self.recovered_count = 0
        self.next_tick = 0
        self._seg_max_tick.clear()
        self._seg_size = 0
        self._obs_segments.set(0)

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.flush()
                if self.fsync != "os":
                    os.fsync(self._fh.fileno())
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def stats(self) -> dict:
        return {
            "recovered_ticks": self.recovered_count,
            "next_tick": self.next_tick,
            "truncations": self.truncations,
            "truncated_bytes": self.truncated_bytes,
            "dropped_segments": self.dropped_segments,
            "duplicate_ticks_skipped": self.duplicate_ticks_skipped,
            "appended_ticks": self.appended_ticks,
            "appended_cursors": self.appended_cursors,
            "appended_bytes": self.appended_bytes,
            "fsyncs": self.fsyncs,
            "rotations": self.rotations,
            "evicted_segments": self.evicted_segments,
            "fsync_policy": self.fsync if self.fsync != "every-n"
            else f"every-{self.fsync_every}",
            "segments": len(_list_segments(self.path)),
        }
