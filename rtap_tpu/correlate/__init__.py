"""Topology-aware incident correlation (ISSUE 9; ROADMAP item 4).

Host-side, cheap, and downstream of the alert stream: per-stream alerts
(keyed by their stable PR 5 ``alert_id``s) fold into cluster-level
incident records — blast-radius detection over node/service adjacency,
the scenario no per-stream detector covers.

- :mod:`rtap_tpu.correlate.topology` — :class:`TopologyMap`: node ->
  service assignment + service links -> connected correlation clusters,
  loaded from a JSON spec (``serve --topology PATH``) or inferred from
  stream-name prefixes (``--topology infer``).
- :mod:`rtap_tpu.correlate.incidents` — :class:`IncidentCorrelator`:
  quiescence-windowed fold of the alert line stream into ``incident``
  events (member alert_ids, blast-radius node set, onset tick,
  attributed fields), exactly-once across kill-9/journal-replay resume,
  exposed at ``GET /incidents`` and via ``rtap_obs_incident_*``.

docs/WORKLOADS.md carries the spec format, the incident schema, and the
triage runbook.
"""

from rtap_tpu.correlate.incidents import IncidentCorrelator, incident_id_of
from rtap_tpu.correlate.topology import TopologyMap

__all__ = ["IncidentCorrelator", "TopologyMap", "incident_id_of"]
