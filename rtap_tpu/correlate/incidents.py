"""Windowed topology-aware incident correlation (ISSUE 9 tentpole b).

The blast radius of a real distributed-systems fault is a correlated
burst of per-stream alerts across ADJACENT nodes — a scenario no
per-stream detector covers (ROADMAP item 4). This host-side layer folds
the alert line stream into cluster-level incident records:

- every emitted alert (keyed by its stable PR 5 ``alert_id``, which is
  what makes the fold crash/replay/failover-safe by construction) lands
  in the open window of its stream's topology cluster
  (:class:`~rtap_tpu.correlate.topology.TopologyMap`);
- a window closes after ``window_s`` seconds of cluster QUIESCENCE (no
  new member) — hysteresis: a re-burst inside the window extends the
  same incident instead of paging a second one — or at the
  ``max_span_s`` hard bound under continuous alerting;
- a closed window with >= ``min_streams`` distinct streams emits ONE
  ``incident`` event line on the alert stream (the operator pages once
  per fault, not once per stream), carrying the member alert_ids, the
  blast-radius node set, onset/end timestamps, and the attributed
  fields aggregated from the members' ``top_fields``; below-threshold
  windows expire silently (the per-stream alert lines already told
  that story).

Crash safety: the incident_id is a pure content hash of the member
alert_ids, and :meth:`IncidentCorrelator.resume_from` re-folds the
alert sink tail through the SAME shared tolerant line walker the resume
suppression scan uses (service/alerts.iter_alert_records). The scan
starts at the ``<alerts>.corr`` sidecar floor — the sink offset at/
under the oldest open window's first member, persisted on window open/
close transitions — because the checkpoints' alert cursors can sit
PAST an open window's earlier members. Replayed already-delivered
alerts are suppressed upstream and re-enter the fold from disk instead;
incidents whose event line landed pre-crash dedupe by id (and the event
line settles its cluster's window mid-scan, pinning the re-fold to the
live closure point); incidents that closed pre-crash but never hit the
disk re-emit. The incident stream is therefore exactly-once across
kill-9 — the workload soak (scripts/workload_soak.py) is the
acceptance proof. Known residual: a window that expired BELOW
min_streams leaves no marker line, so a pipeline-lagged alert whose ts
lands within one tick of the quiescence boundary can merge with the
expired window's members on a re-fold that spans it — a one-tick band,
reachable only when a crash interleaves exactly there, and bounded by
sizing window_s above the pipeline staleness.

Every timestamp here is the SOURCE clock (the loop's monotonic-clamped
tick ts), never the wall clock, so a journal replay reproduces every
close decision bit-for-bit. Choose ``window_s`` comfortably above the
serve pipeline's alert staleness (``pipeline_depth * micro_chunk``
ticks) — a lagged member must still land inside its window.
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque

from rtap_tpu.obs import get_registry

__all__ = ["IncidentCorrelator", "incident_id_of"]

#: hard bound on one window's member list — beyond it, members are
#: counted (``members_dropped``), not stored; a pathological fleet-wide
#: alert storm must not grow host memory without bound
MAX_MEMBERS_PER_WINDOW = 8192

#: remembered already-emitted incident ids (dedupe across resume); FIFO
#: eviction — the window only needs to cover incidents whose members can
#: still be re-folded from the scanned sink tail
MAX_EMITTED_TRACKED = 8192


def incident_id_of(alert_ids) -> str:
    """Deterministic content-derived incident id: a 48-bit blake2b over
    the SORTED member alert_ids. The same fault re-folded after a crash/
    replay/failover reproduces the same id — the dedupe key of the
    exactly-once incident stream. 48 bits (not a 32-bit CRC) because a
    dedupe-key collision SILENTLY suppresses a real incident: at the
    MAX_EMITTED_TRACKED=8192 dedupe horizon the birthday odds are ~0.8%
    for 32 bits vs ~1e-7 here."""
    blob = ",".join(sorted(alert_ids)).encode()
    return f"inc-{hashlib.blake2b(blob, digest_size=6).hexdigest()}"


class _Window:
    __slots__ = ("first_ts", "last_ts", "alert_ids", "streams", "nodes",
                 "fields", "dropped", "start_off")

    def __init__(self, ts: int, start_off: int | None = None):
        self.first_ts = ts
        self.last_ts = ts
        self.alert_ids: list[str] = []
        self.streams: set[str] = set()
        self.nodes: set[str] = set()
        self.fields: dict[str, int] = {}
        self.dropped = 0
        #: alert-sink byte offset BEFORE this window's first member (the
        #: crash-resume re-fold must start at/before it — see sidecar)
        self.start_off = start_off


class IncidentCorrelator:
    """Fold per-stream alerts into cluster-level incidents (module doc).

    Wiring (serve ``--topology``): the AlertWriter calls
    :meth:`observe_alert` per emitted line, the live loop calls
    :meth:`on_tick` once per tick (and per replayed journal row) with
    the tick's source timestamp, and incident events leave through
    ``sink`` (the writer's ``emit_event`` — one stream tells the whole
    story in order). ``snapshot`` backs ``GET /incidents``.
    """

    def __init__(self, topology, window_s: int = 30, min_streams: int = 3,
                 max_span_s: int | None = None, blast_dump_nodes: int = 4,
                 sink=None, flight=None, registry=None,
                 sidecar_path: str | None = None):
        if window_s < 1:
            raise ValueError(f"window_s must be >= 1; got {window_s}")
        if min_streams < 2:
            raise ValueError(
                f"min_streams must be >= 2 (one stream is a per-stream "
                f"alert, not an incident); got {min_streams}")
        self.topology = topology
        self.window_s = int(window_s)
        self.min_streams = int(min_streams)
        # continuous alerting must not hold a window open forever: the
        # hard span bound force-closes (and a genuinely ongoing fault
        # then opens a follow-up incident — operators prefer a second
        # page over a silent hour)
        self.max_span_s = int(max_span_s) if max_span_s is not None \
            else 10 * self.window_s
        if self.max_span_s < self.window_s:
            raise ValueError(
                f"max_span_s must be >= window_s; got {self.max_span_s} "
                f"< {self.window_s}")
        self.blast_dump_nodes = int(blast_dump_nodes)
        self.sink = sink
        self.flight = flight
        # crash-resume scan floor (``<alerts>.corr``, the ``.epoch``
        # sidecar idiom): the sink byte offset at/under the oldest OPEN
        # window's first member. The checkpoints' alert cursors alone
        # are NOT a safe re-fold start — a checkpoint taken mid-window
        # has a cursor PAST that window's earlier members, and a re-fold
        # from it would rebuild a smaller member set whose content-hash
        # incident_id differs from the uninterrupted run's (a duplicate/
        # divergent page). A stale-small sidecar only lengthens the
        # scan, never breaks it, so updates happen on the rare window
        # open/close transitions, not per fold.
        self.sidecar_path = sidecar_path
        self._sidecar_written: int | None = None
        self._open: dict[str, _Window] = {}
        # the loop thread folds/closes while the obs server's HTTP
        # thread snapshots (/incidents): one re-entrant lock (resume_from
        # re-enters observe_alert/on_tick) keeps the container iteration
        # safe. Uncontended acquire is ~100 ns against a ~4 us fold
        # (selfbench) — far inside the 1% tick-budget gate.
        self._lock = threading.RLock()
        self._emitted: set[str] = set()
        self._emitted_order: deque = deque()
        #: recent incident records (bounded), newest last — /incidents
        self._recent: deque = deque(maxlen=256)
        self._replaying = False
        self._replay_pending: list[dict] = []
        self._last_now_ts = 0  # the correlation clock's latest position
        # counters/gauges (docs/TELEMETRY.md incident section)
        obs = registry if registry is not None else get_registry()
        self._obs_incidents = obs.counter(
            "rtap_obs_incidents_total",
            "cluster-level incidents emitted onto the alert stream")
        self._obs_correlated = obs.counter(
            "rtap_obs_incident_alerts_correlated_total",
            "alert lines folded into correlation windows")
        self._obs_open = obs.gauge(
            "rtap_obs_incident_open_windows",
            "correlation windows currently open (one per alerting "
            "topology cluster)")
        self._obs_members = obs.histogram(
            "rtap_obs_incident_members",
            "member alert count per emitted incident")
        self._obs_blast = obs.histogram(
            "rtap_obs_incident_blast_nodes",
            "blast-radius node count per emitted incident")
        self._obs_expired = obs.counter(
            "rtap_obs_incident_windows_expired_total",
            "correlation windows that closed below min_streams (the "
            "per-stream alerts already told that story)")
        self._obs_deduped = obs.counter(
            "rtap_obs_incident_resume_deduped_total",
            "incidents suppressed on resume because their event line "
            "already reached the sink (exactly-once across a crash)")
        # plain-int mirrors for stats()
        self.incidents = 0
        self.correlated = 0
        self.expired = 0
        self.deduped = 0
        self.members_dropped = 0

    # ---- the fold ----
    def observe_alert(self, alert_id: str | None, stream: str, ts: int,
                      top_fields=None, sink_offset: int | None = None) -> None:
        """Fold one emitted alert into its cluster's open window.
        ``sink_offset`` is the alert sink's byte offset BEFORE the batch
        carrying this alert (the AlertWriter passes it) — it anchors the
        crash-resume sidecar floor."""
        with self._lock:
            self._observe_alert(alert_id, stream, ts, top_fields,
                                sink_offset)

    def _observe_alert(self, alert_id, stream, ts, top_fields,
                       sink_offset=None) -> None:
        ts = int(ts)
        cluster = self.topology.cluster_of(stream)
        w = self._open.get(cluster)
        if w is None:
            w = self._open[cluster] = _Window(ts, start_off=sink_offset)
            self._obs_open.set(len(self._open))
            self._update_sidecar()
        w.last_ts = max(w.last_ts, ts)
        w.first_ts = min(w.first_ts, ts)
        if len(w.alert_ids) < MAX_MEMBERS_PER_WINDOW:
            if alert_id is not None:
                w.alert_ids.append(alert_id)
        else:
            # storm bound: members beyond the cap are counted, not
            # stored — but the blast radius (streams/nodes) and field
            # attribution keep accumulating below (bounded by fleet
            # size), so min_streams decisions and blast_dump_nodes
            # triggers never under-count in a fleet-wide storm
            w.dropped += 1
            self.members_dropped += 1
        w.streams.add(stream)
        w.nodes.add(self.topology.node_of(stream))
        for tf in top_fields or ():
            name = tf.get("name", f"f{tf.get('field', '?')}")
            w.fields[name] = w.fields.get(name, 0) + 1
        self.correlated += 1
        self._obs_correlated.inc()

    def on_tick(self, now_ts: int | None, tick: int = 0,
                sink_offset: int | None = None) -> list[dict]:
        """Advance the correlation clock; close quiesced/over-span
        windows. Returns the incident records emitted this call (the
        soaks assert on them without re-parsing the sink).
        ``sink_offset`` (the writer's current offset, passed by the
        loop) advances the crash-resume sidecar floor once no windows
        remain open."""
        if now_ts is None:
            return []
        now_ts = int(now_ts)
        self._last_now_ts = max(self._last_now_ts, now_ts)
        emitted = []
        with self._lock:
            closed_any = False
            for cluster in sorted(self._open):
                w = self._open[cluster]
                if (now_ts - w.last_ts > self.window_s
                        or now_ts - w.first_ts > self.max_span_s):
                    del self._open[cluster]
                    closed_any = True
                    rec = self._close(cluster, w, tick)
                    if rec is not None:
                        emitted.append(rec)
            if closed_any:
                self._obs_open.set(len(self._open))
                self._update_sidecar(idle_offset=sink_offset)
        return emitted

    def oldest_open_age_s(self, now_ts: int | None = None) -> float:
        """Age (source-clock seconds) of the oldest OPEN correlation
        window — the incident-close lag the latency layer exposes as a
        first-class gauge (ISSUE 11): how far behind the incident stream
        can be running relative to the per-stream alerts feeding it.
        0.0 with no open windows."""
        now = int(now_ts) if now_ts is not None else self._last_now_ts
        with self._lock:
            if not self._open:
                return 0.0
            first = min(w.first_ts for w in self._open.values())
        return float(max(0, now - first))

    def _update_sidecar(self, idle_offset: int | None = None) -> None:
        """Persist the re-fold floor: the min start offset over open
        windows, or ``idle_offset`` (the current sink end) when none are
        open. Atomic tmp+rename; failures are ignored (a stale-small
        floor is safe — it only lengthens the resume scan)."""
        if self.sidecar_path is None:
            return
        starts = [w.start_off for w in self._open.values()
                  if w.start_off is not None]
        if starts:
            floor = min(starts)
        elif not self._open and idle_offset is not None:
            floor = int(idle_offset)
        else:
            return  # unknown floor: keep the last persisted (safe)
        if floor == self._sidecar_written:
            return
        import json
        import os
        try:
            tmp = self.sidecar_path + ".tmp"
            with open(tmp, "w") as f:
                f.write(json.dumps({"offset": floor}))
            os.replace(tmp, self.sidecar_path)
            self._sidecar_written = floor
        except OSError:
            pass

    def resume_scan_offset(self, cursor_offset: int) -> int:
        """Where the crash-resume re-fold must start: the persisted
        sidecar floor when present (it covers windows open at the
        crash), clamped to the checkpoints' alert cursor. NO sidecar
        means no window ever opened under correlation — the common case
        is arming --topology on a deployment whose sink already carries
        history, and a byte-0 scan there would close each long-past
        burst's window as the scan clock walks by and PAGE a stale
        incident per historical fault (nothing on the stream dedupes
        them: correlation was never armed). Scan from the cursor — the
        post-checkpoint tail is the only span whose alerts can still
        belong to a live window."""
        import json
        try:
            with open(self.sidecar_path) as f:
                off = int(json.load(f).get("offset", 0))
            return max(0, min(off, cursor_offset))
        except (OSError, ValueError, TypeError):
            return max(0, int(cursor_offset))

    def _close(self, cluster: str, w: _Window, tick: int) -> dict | None:
        if len(w.streams) < self.min_streams:
            self.expired += 1
            self._obs_expired.inc()
            return None
        rec = {
            "event": "incident",
            "incident_id": incident_id_of(w.alert_ids),
            "cluster": cluster,
            "members": len(w.alert_ids),
            "alert_ids": sorted(w.alert_ids),
            "streams": sorted(w.streams),
            "nodes": sorted(w.nodes),
            "onset_ts": int(w.first_ts),
            "end_ts": int(w.last_ts),
            "span_s": int(w.last_ts - w.first_ts),
            # attributed field names ranked by how many members named
            # them (count-desc, then name for determinism) — the counts
            # are the ranking, the list stays a plain name list
            "fields": sorted(w.fields, key=lambda n: (-w.fields[n], n)),
            **({"members_dropped": w.dropped} if w.dropped else {}),
        }
        if self._replaying:
            # a close reached during the resume scan may belong to an
            # incident whose event line appears LATER in the file —
            # buffer, and let resume_from settle emission once the
            # already-emitted id set is complete
            self._replay_pending.append(rec)
            return None
        return self._emit(rec, tick)

    def _emit(self, rec: dict, tick: int) -> dict | None:
        iid = rec["incident_id"]
        if iid in self._emitted:
            self.deduped += 1
            self._obs_deduped.inc()
            return None
        self._emitted.add(iid)
        self._emitted_order.append(iid)
        while len(self._emitted_order) > MAX_EMITTED_TRACKED:
            self._emitted.discard(self._emitted_order.popleft())
        self.incidents += 1
        self._obs_incidents.inc()
        self._obs_members.observe(rec["members"])
        self._obs_blast.observe(len(rec["nodes"]))
        self._recent.append(rec)
        if self.sink is not None:
            self.sink(rec)
        if self.flight is not None and \
                len(rec["nodes"]) >= self.blast_dump_nodes:
            # a large-blast incident is a black-box moment: capture the
            # window that produced it, like a quarantine does
            self.flight.request_dump("incident", tick)
        return rec

    # ---- crash/replay resume ----
    def resume_from(self, path: str, offset: int = 0) -> dict:
        """Rebuild correlation state from the alert sink tail (one
        shared tolerant walker — service/alerts.iter_alert_records):
        already-emitted incident ids seed the dedupe set, trailing alert
        lines re-fold into windows, and incidents that closed pre-crash
        without their event line reaching the disk re-emit. Returns a
        small summary for stats/logs."""
        from rtap_tpu.service.alerts import iter_alert_records

        with self._lock:
            return self._resume_from(path, offset, iter_alert_records)

    def _resume_from(self, path, offset, iter_alert_records) -> dict:
        self._replaying = True
        scanned = alerts = 0
        try:
            for kind, rec in iter_alert_records(path, offset):
                scanned += 1
                if kind == "event":
                    if rec.get("event") == "incident" \
                            and rec.get("incident_id"):
                        iid = rec["incident_id"]
                        if iid not in self._emitted:
                            self._emitted.add(iid)
                            self._emitted_order.append(iid)
                        self._recent.append(rec)
                        # the event line marks EXACTLY where live closed
                        # this cluster's window: settle it (its members
                        # are this incident's — deduped above). Without
                        # this, a pipeline-lagged alert whose ts sits
                        # just inside the window band would merge into
                        # the already-closed window on re-fold (the scan
                        # clock only advances at alert timestamps, which
                        # trail the live tick clock) and emit a
                        # divergent-id duplicate.
                        if rec.get("cluster") in self._open:
                            del self._open[rec["cluster"]]
                    continue
                if kind != "alert":
                    continue
                ts = rec.get("ts")
                stream = rec.get("stream")
                if ts is None or stream is None:
                    continue
                alerts += 1
                # drive closure with the stream clock as the scan walks
                # forward — to ts-1, NOT ts: live folds a tick's alerts
                # BEFORE its on_tick, so the last close decision live
                # made before folding this record saw the PREVIOUS
                # second. Advancing to ts here would close a window this
                # record merged into live (a member landing at a gap of
                # exactly window_s+1), re-folding a smaller member set
                # whose content hash diverges from the emitted id.
                self.on_tick(int(ts) - 1)
                # anchor any window this re-fold re-opens at the scan
                # start: its earliest member sits at/after that byte, and
                # a start_off=None window would drop out of the sidecar
                # floor min — a cluster opening LIVE later would then
                # persist a floor past this window's members, and a
                # second crash would re-fold a smaller member set and
                # hash a divergent incident_id (exactly-once violated)
                self.observe_alert(rec.get("alert_id"), stream, int(ts),
                                   top_fields=rec.get("top_fields"),
                                   sink_offset=offset)
        finally:
            self._replaying = False
        re_emitted = 0
        for rec in self._replay_pending:
            if self._emit(rec, 0) is not None:
                re_emitted += 1
        self._replay_pending.clear()
        self._obs_open.set(len(self._open))
        return {"scanned": scanned, "alerts_refolded": alerts,
                "incidents_known": len(self._emitted),
                "re_emitted": re_emitted}

    # ---- exposition ----
    def snapshot(self) -> dict:
        """Point-in-time view for ``GET /incidents`` (same diagnostic
        read contract as /trace and /health; the lock makes a read taken
        mid-fold from the obs HTTP thread safe, not stale-free)."""
        with self._lock:
            return {
                "incidents": list(self._recent),
                "open_windows": {
                    cluster: {
                        "members": len(w.alert_ids),
                        "streams": len(w.streams),
                        "nodes": sorted(w.nodes),
                        "first_ts": int(w.first_ts),
                        "last_ts": int(w.last_ts),
                    }
                    for cluster, w in sorted(self._open.items())
                },
                "window_s": self.window_s,
                "min_streams": self.min_streams,
                "topology": self.topology.stats(),
                **self.stats(),
            }

    def stats(self) -> dict:
        return {
            "incidents_emitted": self.incidents,
            "alerts_correlated": self.correlated,
            "windows_expired": self.expired,
            "resume_deduped": self.deduped,
            "members_dropped": self.members_dropped,
            "open_clusters": len(self._open),
        }
