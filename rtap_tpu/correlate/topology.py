"""Node/service topology for incident correlation (ISSUE 9).

The correlator groups per-stream alerts by WHERE they happened: streams
belong to nodes (``node03.cpu`` -> ``node03``), nodes belong to services,
and services may be linked (a dependency edge — a database brown-out
pages its web tier too). Two nodes are ADJACENT when their services are
the same or linked; the correlator folds alerts per connected component
of that adjacency graph (the blast-radius unit).

Two construction paths, one class:

- :meth:`TopologyMap.from_spec` — an operator-authored JSON spec::

      {"services": {"web": ["node00", "node01"], "db": ["node02"]},
       "links": [["web", "db"]]}

  Every node name is a stream-id prefix (the part before the last
  ``.``); unknown nodes fall into the ``"?"`` catch-all service so a
  stream outside the spec degrades to per-node correlation instead of
  crashing the serve loop.

- :meth:`TopologyMap.infer` — zero-config inference from stream-name
  prefixes: node = prefix before the last ``.``, service = the node
  name with its trailing digits (and separator) stripped, so
  ``web-01.cpu``/``web-02.mem`` share service ``web`` and
  ``node00003.net`` lands in ``node``. No links. This is the
  ``serve --topology infer`` path and matches both synthetic-generator
  naming families (``node{i:05d}.{metric}``, ``{svc}-{i:02d}.{metric}``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

__all__ = ["TopologyMap"]

#: catch-all service for nodes a spec does not name: they still correlate
#: (with each other per node), never crash the loop
UNKNOWN_SERVICE = "?"


def node_of_stream(stream_id: str) -> str:
    """Stream id -> node name: the prefix before the LAST dot (the
    repo-wide ``<node>.<metric>`` naming); a dotless id is its own node."""
    node, sep, _metric = stream_id.rpartition(".")
    return node if sep else stream_id


def service_of_node(node: str) -> str:
    """Inference rule: strip trailing digits and one trailing separator,
    so ``web-01`` -> ``web``, ``node00003`` -> ``node``, ``db2`` -> ``db``.
    An all-digit node keeps its full name (its own service)."""
    base = node.rstrip("0123456789")
    base = base.rstrip("-_.")
    return base if base else node


@dataclass
class TopologyMap:
    """node -> service assignment + service adjacency -> connected
    components (the correlation clusters)."""

    #: node name -> service name
    services: dict[str, str] = field(default_factory=dict)
    #: undirected service-dependency edges
    links: list[tuple[str, str]] = field(default_factory=list)
    #: True = nodes absent from `services` infer their service by prefix
    #: (the zero-config mode); False = they fold into UNKNOWN_SERVICE
    infer_unknown: bool = False

    def __post_init__(self) -> None:
        self._component: dict[str, str] = {}
        self._rebuild_components()

    # ---- construction ----
    @classmethod
    def from_spec(cls, spec: dict | str) -> "TopologyMap":
        """Build from a spec dict, a JSON string, or a file path."""
        if isinstance(spec, str):
            if spec.lstrip().startswith("{"):
                spec = json.loads(spec)
            else:
                with open(spec) as f:
                    spec = json.load(f)
        if not isinstance(spec, dict) or "services" not in spec:
            raise ValueError(
                'topology spec must be an object with a "services" map '
                '({"services": {"svc": ["node", ...]}, "links": [...]})')
        services: dict[str, str] = {}
        for svc, nodes in spec["services"].items():
            if not isinstance(nodes, (list, tuple)):
                raise ValueError(
                    f'topology spec: services[{svc!r}] must be a node list')
            for node in nodes:
                if node in services:
                    raise ValueError(
                        f"topology spec: node {node!r} appears in services "
                        f"{services[node]!r} and {svc!r}")
                services[str(node)] = str(svc)
        links = []
        for pair in spec.get("links", []):
            if not (isinstance(pair, (list, tuple)) and len(pair) == 2):
                raise ValueError(
                    f"topology spec: links entries are [svcA, svcB] pairs; "
                    f"got {pair!r}")
            links.append((str(pair[0]), str(pair[1])))
        known = set(spec["services"])
        for a, b in links:
            missing = {a, b} - known
            if missing:
                raise ValueError(
                    f"topology spec: link {(a, b)} names undeclared "
                    f"service(s) {sorted(missing)}")
        return cls(services=services, links=links)

    @classmethod
    def infer(cls) -> "TopologyMap":
        """Zero-config topology: every node's service is its stripped
        name prefix (see :func:`service_of_node`), no links."""
        return cls(infer_unknown=True)

    # ---- queries ----
    def service_of(self, node: str) -> str:
        svc = self.services.get(node)
        if svc is not None:
            return svc
        return service_of_node(node) if self.infer_unknown else UNKNOWN_SERVICE

    def node_of(self, stream_id: str) -> str:
        return node_of_stream(stream_id)

    def cluster_of(self, stream_id: str) -> str:
        """Stream id -> correlation-cluster key: the connected component
        (over service links) of the stream's node's service. Services
        never declared and never linked are their own component."""
        return self._component_of(self.service_of(self.node_of(stream_id)))

    def adjacent(self, node_a: str, node_b: str) -> bool:
        """Blast-radius adjacency: same service, or linked services
        (transitively — components are the correlation unit)."""
        return self._component_of(self.service_of(node_a)) \
            == self._component_of(self.service_of(node_b))

    # ---- internals ----
    def _rebuild_components(self) -> None:
        parent: dict[str, str] = {}

        def find(x: str) -> str:
            parent.setdefault(x, x)
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for svc in sorted(set(self.services.values())):
            find(svc)
        for a, b in self.links:
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[ra] = rb
        # canonical component name: lexicographically smallest member, so
        # cluster keys are deterministic across processes/restarts
        members: dict[str, list[str]] = {}
        for svc in parent:
            members.setdefault(find(svc), []).append(svc)
        self._component = {
            svc: min(group)
            for root, group in members.items() for svc in group
        }

    def _component_of(self, svc: str) -> str:
        got = self._component.get(svc)
        if got is not None:
            return got
        # an inferred/unknown service unseen at build time is its own
        # component; cache so repeated lookups stay O(1)
        self._component[svc] = svc
        return svc

    def stats(self) -> dict:
        return {
            "declared_nodes": len(self.services),
            "services": len(set(self.services.values())),
            "links": len(self.links),
            "inferring": self.infer_unknown,
        }
