from rtap_tpu.data.synthetic import SyntheticStreamConfig, generate_stream  # noqa: F401
from rtap_tpu.data.nab_corpus import NabFile, load_corpus, ensure_standin_corpus  # noqa: F401
