"""NAB-format corpus IO + offline stand-in generation.

The reference is evaluated on the Numenta Anomaly Benchmark (SURVEY.md L6,
§3.4): a corpus of CSV files (`timestamp,value`, '%Y-%m-%d %H:%M:%S' stamps)
plus `labels/combined_windows.json` mapping each relative CSV path to a list
of [start, end] anomaly windows.

The real corpus is not present in this offline environment (SURVEY.md §6
blocker), so `ensure_standin_corpus` materializes a deterministic synthetic
corpus in the exact NAB on-disk format — including a file named
`realAWSCloudwatch/ec2_cpu_utilization_5f5533.csv` so benchmark configs 1-2
(BASELINE.md) run mechanically, and swap seamlessly to the real corpus the
moment one appears at NAB_CORPUS_ENV or data/nab/.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from datetime import datetime, timezone
from pathlib import Path

import numpy as np

from rtap_tpu.data.synthetic import LabeledStream, SyntheticStreamConfig, generate_stream

NAB_CORPUS_ENV = "RTAP_NAB_CORPUS"
TS_FMT = "%Y-%m-%d %H:%M:%S"

# Stand-in corpus layout: (relative name, metric profile, rows). 5-min cadence
# like real NAB. First entry is the config-1 benchmark stream.
STANDIN_FILES = [
    ("realAWSCloudwatch/ec2_cpu_utilization_5f5533.csv", "cpu", 4032),
    ("realAWSCloudwatch/ec2_cpu_utilization_24ae8d.csv", "cpu", 4032),
    ("realAWSCloudwatch/ec2_network_in_257a54.csv", "net", 4032),
    ("realAWSCloudwatch/ec2_disk_write_bytes_1ef3de.csv", "disk_io", 4032),
    ("realAWSCloudwatch/rds_cpu_utilization_e47b3b.csv", "cpu", 4032),
    ("realAWSCloudwatch/elb_request_count_8c0756.csv", "net", 4032),
    ("synthetic/node_mem_leak.csv", "mem", 4032),
    ("synthetic/node_latency_burst.csv", "latency_ms", 4032),
]


@dataclass
class NabFile:
    """One corpus file: timestamps (unix sec), values, label windows."""

    name: str  # relative path, e.g. "realAWSCloudwatch/ec2_cpu_utilization_5f5533.csv"
    timestamps: np.ndarray  # int64 unix seconds [T]
    values: np.ndarray  # float32 [T]
    windows: list[tuple[int, int]]  # [(start_unix, end_unix)]


def _parse_ts(s: str) -> int:
    # NAB stamps may carry fractional seconds in labels; truncate.
    s = s.split(".")[0]
    return int(datetime.strptime(s, TS_FMT).replace(tzinfo=timezone.utc).timestamp())


def _fmt_ts(unix: int) -> str:
    return datetime.fromtimestamp(int(unix), tz=timezone.utc).strftime(TS_FMT)


def load_corpus(root: str | Path, subset: str | None = None) -> list[NabFile]:
    """Load a NAB-format corpus: root/data/**/*.csv + root/labels/combined_windows.json.

    `subset` filters by relative-path prefix (e.g. "realAWSCloudwatch").
    """
    root = Path(root)
    data_dir = root / "data"
    with open(root / "labels" / "combined_windows.json") as f:
        label_map = json.load(f)
    out: list[NabFile] = []
    for csv_path in sorted(data_dir.rglob("*.csv")):
        rel = csv_path.relative_to(data_dir).as_posix()
        if subset and not rel.startswith(subset):
            continue
        ts, vals = [], []
        with open(csv_path) as f:
            header = f.readline()  # "timestamp,value"
            assert "timestamp" in header
            for line in f:
                t_str, v_str = line.rstrip("\n").split(",")[:2]
                ts.append(_parse_ts(t_str))
                vals.append(float(v_str))
        windows = [(_parse_ts(a), _parse_ts(b)) for a, b in label_map.get(rel, [])]
        out.append(NabFile(rel, np.asarray(ts, np.int64), np.asarray(vals, np.float32), windows))
    return out


def write_corpus(root: str | Path, files: list[NabFile]) -> None:
    """Write files in NAB on-disk format (data/ CSVs + labels json)."""
    root = Path(root)
    label_map: dict[str, list[list[str]]] = {}
    for nf in files:
        p = root / "data" / nf.name
        p.parent.mkdir(parents=True, exist_ok=True)
        with open(p, "w") as f:
            f.write("timestamp,value\n")
            for t, v in zip(nf.timestamps, nf.values):
                f.write(f"{_fmt_ts(t)},{v:.5f}\n")
        label_map[nf.name] = [[_fmt_ts(a), _fmt_ts(b)] for a, b in nf.windows]
    (root / "labels").mkdir(parents=True, exist_ok=True)
    with open(root / "labels" / "combined_windows.json", "w") as f:
        json.dump(label_map, f, indent=2, sort_keys=True)


def _standin_files(seed: int = 7) -> list[NabFile]:
    out = []
    for rel, metric, rows in STANDIN_FILES:
        # noise_scale keeps the stand-in as smooth as real CloudWatch series:
        # per-step noise must stay within ~1 encoder bucket (range/130) or the
        # TM never converges and anomalies drown in baseline jitter
        cfg = SyntheticStreamConfig(
            length=rows, cadence_s=300.0, metric=metric, n_anomalies=3,
            anomaly_magnitude=8.0, noise_scale=0.35,
            kinds=("spike", "level_shift", "dropout"),
        )
        ls: LabeledStream = generate_stream(rel, cfg, seed=seed)
        out.append(NabFile(rel, ls.timestamps, ls.values, ls.windows))
    return out


def ensure_standin_corpus(root: str | Path | None = None, seed: int = 7) -> Path:
    """Return a corpus root, generating the synthetic stand-in if needed.

    Resolution order: explicit `root` (always honored, for test isolation) ->
    $RTAP_NAB_CORPUS (a real NAB checkout, if the driver provides one) ->
    <repo>/data/nab (generated stand-in, cached on disk).
    """
    if root is None:
        env = os.environ.get(NAB_CORPUS_ENV)
        if env and (Path(env) / "labels" / "combined_windows.json").exists():
            return Path(env)
        root = Path(__file__).resolve().parents[2] / "data" / "nab"
    root = Path(root)
    marker = root / "labels" / "combined_windows.json"
    if not marker.exists():
        write_corpus(root, _standin_files(seed))
    return root
