"""Synthetic cluster-metric generator with labeled fault injection.

Replaces the reference's monitored cluster + fault-injection rig (SURVEY.md
C17/C21 and §3.5): instead of stressing a live Kubernetes deployment with
cpu-burn / tc-netem / node-kill, we synthesize per-node per-metric time
series (diurnal sine + noise, metric-specific baselines) and inject labeled
anomalies — spike, level shift, drift, stuck-at, dropout — recording ground
-truth windows in NAB's `combined_windows.json` shape. Deterministic per
(seed, stream id): the same corpus regenerates bit-identically anywhere.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field, replace
from typing import Sequence

import numpy as np

from rtap_tpu.utils.hashing import hash_u32_np

ANOMALY_KINDS = ("spike", "level_shift", "drift", "stuck", "dropout")

# Per-metric (baseline, diurnal amplitude, noise sigma, clip range)
METRIC_PROFILES = {
    "cpu": (35.0, 20.0, 3.0, (0.0, 100.0)),
    "mem": (55.0, 10.0, 1.5, (0.0, 100.0)),
    "net": (20.0, 15.0, 5.0, (0.0, None)),
    "disk_io": (10.0, 6.0, 2.5, (0.0, None)),
    "latency_ms": (12.0, 4.0, 2.0, (0.0, None)),
}


@dataclass(frozen=True)
class SyntheticStreamConfig:
    length: int = 4000
    cadence_s: float = 1.0
    metric: str = "cpu"
    period_s: float = 86400.0  # diurnal
    n_anomalies: int = 3
    anomaly_magnitude: float = 4.0  # in units of (scaled) noise sigma
    noise_scale: float = 1.0  # multiplier on the metric's noise sigma
    # AR(1) coefficient of the noise: real node metrics are autocorrelated
    # (load moves smoothly), not white. 0 = iid Gaussian (legacy default);
    # ~0.85 makes per-tick deltas small relative to the stationary sigma, the
    # regime where an HTM at NAB-rule resolution can learn the baseline.
    noise_phi: float = 0.0
    # which fault kinds to inject; "drift" and "stuck" are near-invisible to
    # point-anomaly detectors by design (gradual / too-regular) — include them
    # only when evaluating that hard class
    kinds: tuple[str, ...] = ANOMALY_KINDS
    start_unix: int = 1_700_000_000
    # earliest injection point, as a fraction of the stream. Evaluations set
    # this past the detector's likelihood probation (a fault injected while
    # the likelihood is still flat-0.5 is undetectable by construction and
    # would poison recall with a measurement artifact, not a detector miss).
    inject_after_frac: float = 0.25
    # Signal family. "diurnal" is the original sine+AR(1) generator every
    # committed quality figure was tuned on. "heldout" is a deliberately
    # DIFFERENT world for external validation (r4 verdict: the 32-col
    # density headline's quality evidence was self-referential): Student-t
    # heavy-tailed innovations, 2-state volatility bursts, a per-stream
    # linear trend, and UNLABELED benign level shifts (regime switches the
    # detector must absorb, not alert on). Fault injection/labeling is
    # shared between families; magnitudes stay anchored to the metric's
    # NOMINAL sigma so "6-sigma" means the same thing in both worlds.
    family: str = "diurnal"


@dataclass(frozen=True)
class FaultEvent:
    """Ground truth for one injected fault (SURVEY.md §3.5 eval unit)."""

    kind: str  # one of ANOMALY_KINDS
    onset: int  # unix sec the fault begins
    end: int  # unix sec the injected interval ends
    window: tuple[int, int]  # labeled detection window (onset/end + margin)


@dataclass
class LabeledStream:
    """One generated stream: values + ground-truth anomaly windows."""

    stream_id: str
    timestamps: np.ndarray  # int64 unix seconds, [T]
    values: np.ndarray  # float32, [T]
    windows: list[tuple[int, int]] = field(default_factory=list)  # unix-sec spans
    events: list[FaultEvent] = field(default_factory=list)  # kind-labeled faults


def _rng_for(seed: int, stream_id: str) -> np.random.Generator:
    # zlib.crc32 is process-independent (unlike builtin hash with its salt),
    # keeping the "regenerates bit-identically anywhere" contract.
    sid_hash = int(hash_u32_np(np.uint32(zlib.crc32(stream_id.encode())), seed))
    return np.random.Generator(np.random.Philox(key=(seed, sid_hash)))


def _inject(
    signal: np.ndarray, t_unix: np.ndarray, rng: np.random.Generator,
    cfg: SyntheticStreamConfig, sigma: float, kind: str, c: int, dur: int,
) -> tuple[tuple[int, int], FaultEvent]:
    """Inject one `kind` fault centered at index `c` into `signal` in place;
    -> (window, event). Extracted verbatim from generate_stream so the
    per-stream and per-node generators share one fault vocabulary (and
    generate_stream's rng draw order — the bit-identical-regeneration
    contract — is unchanged)."""
    s, e = int(c), min(int(c) + dur, len(signal) - 1)
    mag = cfg.anomaly_magnitude * sigma
    if kind == "spike":
        signal[s : s + max(1, dur // 4)] += mag * rng.choice([-1.0, 1.0])
    elif kind == "level_shift":
        signal[s:] += mag * rng.choice([-1.0, 1.0])
    elif kind == "drift":
        ramp = np.linspace(0.0, mag, e - s)
        signal[s:e] += ramp
        signal[e:] += mag
    elif kind == "stuck":
        signal[s:e] = signal[s]
    elif kind == "dropout":
        signal[s:e] = 0.0
    margin = max(2, dur // 2)
    win = (int(t_unix[max(0, s - margin)]), int(t_unix[min(len(signal) - 1, e + margin)]))
    return win, FaultEvent(kind, int(t_unix[s]), int(t_unix[e]), win)


def _heldout_base(
    rng: np.random.Generator, cfg: SyntheticStreamConfig, base: float,
    amp: float, sigma: float, t_idx: np.ndarray, phase: float,
) -> np.ndarray:
    """Held-out-family base signal (no faults yet): heavy-tailed bursty
    AR noise + diurnal + trend + unlabeled benign regime switches.

    - Innovations are Student-t (df=3, scaled to unit variance): real ops
      metrics have far heavier tails than the Gaussian the tuned-on family
      uses, so likelihood tails face in-distribution outliers.
    - A 2-state volatility chain (calm sigma / 2.5x burst sigma, mean dwell
      ~200/40 ticks) makes variance non-stationary.
    - A per-stream linear trend (+-[0.5, 2] sigma over the stream) breaks
      the stationary-baseline assumption.
    - 1-3 benign level shifts of +-(1..1.5) sigma at random times are NOT
      labeled: a regime switch the detector must absorb. They are kept
      below fault scale (faults sweep 2-6 sigma) but are real precision
      hazards for over-sensitive configs.
    """
    n = len(t_idx)
    innov = rng.standard_t(3, n) / np.sqrt(3.0)
    # volatility chain: geometric dwell times, calm <-> burst
    vol = np.empty(n, np.float64)
    i, burst = 0, False
    while i < n:
        dwell = int(rng.geometric(1.0 / (40.0 if burst else 200.0)))
        vol[i : i + dwell] = 2.5 if burst else 1.0
        i += dwell
        burst = not burst
    phi = max(cfg.noise_phi, 0.9)  # smooth like real node metrics
    noise = np.empty(n, np.float64)
    prev = 0.0
    scaled = innov * vol * sigma * np.sqrt(1.0 - phi * phi)
    for j in range(n):
        prev = phi * prev + scaled[j]
        noise[j] = prev
    slope_total = rng.uniform(0.5, 2.0) * sigma * rng.choice([-1.0, 1.0])
    trend = slope_total * (t_idx / max(n - 1, 1))
    regime = np.zeros(n, np.float64)
    for _ in range(int(rng.integers(1, 4))):
        at = int(rng.integers(int(n * 0.1), n - 1))
        regime[at:] += rng.uniform(1.0, 1.5) * sigma * rng.choice([-1.0, 1.0])
    return (
        base
        + amp * np.sin(2 * np.pi * t_idx * cfg.cadence_s / cfg.period_s + phase)
        + trend + regime + noise
    )


def generate_stream(
    stream_id: str, cfg: SyntheticStreamConfig, seed: int = 0
) -> LabeledStream:
    """Generate one labeled stream.

    The base signal is baseline + diurnal sine (phase hashed from stream id)
    + Gaussian noise; `cfg.n_anomalies` injections are placed in the
    post-probation region with jittered spacing, each a random kind from
    ANOMALY_KINDS. Window labels span the injected interval plus a small
    margin, mirroring how NAB windows surround each anomaly.
    """
    rng = _rng_for(seed, stream_id)
    base, amp, sigma, clip = METRIC_PROFILES.get(cfg.metric, METRIC_PROFILES["cpu"])
    sigma = sigma * cfg.noise_scale
    t_idx = np.arange(cfg.length, dtype=np.float64)
    t_unix = (cfg.start_unix + t_idx * cfg.cadence_s).astype(np.int64)
    phase = rng.uniform(0, 2 * np.pi)
    if cfg.family == "heldout":
        signal = _heldout_base(rng, cfg, base, amp, sigma, t_idx, phase)
    elif cfg.family == "diurnal":
        # draw order below is the bit-identical-regeneration contract for
        # every committed artifact — never reorder
        noise = rng.normal(0.0, sigma, cfg.length)
        if cfg.noise_phi > 0.0:
            # AR(1), stationary std == sigma: x_t = phi*x_{t-1} + eps*sqrt(1-phi^2)
            noise *= np.sqrt(1.0 - cfg.noise_phi**2)
            for i in range(1, cfg.length):
                noise[i] += cfg.noise_phi * noise[i - 1]
        signal = (
            base
            + amp * np.sin(2 * np.pi * t_idx * cfg.cadence_s / cfg.period_s + phase)
            + noise
        )
    else:
        raise ValueError(f"unknown signal family {cfg.family!r} "
                         "(expected 'diurnal' or 'heldout')")

    windows: list[tuple[int, int]] = []
    events: list[FaultEvent] = []
    if cfg.n_anomalies > 0:
        # keep injections clear of the likelihood probation region
        lo = int(cfg.length * cfg.inject_after_frac)
        n_candidates = cfg.length - 50 - lo
        if n_candidates < cfg.n_anomalies:
            # same guard as generate_node: a degenerate candidate range would
            # otherwise surface as an opaque numpy ValueError
            raise ValueError(
                f"stream length {cfg.length} too short: the injection range "
                f"[{lo}, {cfg.length - 50}) has {max(n_candidates, 0)} candidate "
                f"centers for n_anomalies={cfg.n_anomalies}; lengthen the stream "
                "or lower inject_after_frac/n_anomalies"
            )
        centers = np.sort(rng.choice(np.arange(lo, cfg.length - 50), size=cfg.n_anomalies, replace=False))
        for c in centers:
            kind = cfg.kinds[rng.integers(len(cfg.kinds))]
            dur = int(rng.integers(5, 40))
            win, ev = _inject(signal, t_unix, rng, cfg, sigma, kind, int(c), dur)
            windows.append(win)
            events.append(ev)

    if clip[0] is not None:
        signal = np.maximum(signal, clip[0])
    if clip[1] is not None:
        signal = np.minimum(signal, clip[1])
    return LabeledStream(stream_id, t_unix, signal.astype(np.float32), windows, events)


def generate_cluster(
    n_nodes: int,
    metrics: Sequence[str] = ("cpu", "mem", "net"),
    cfg: SyntheticStreamConfig | None = None,
    seed: int = 0,
) -> list[LabeledStream]:
    """`n_nodes * len(metrics)` labeled streams, ids `node{i:05d}.{metric}`."""
    cfg = cfg or SyntheticStreamConfig()
    out = []
    for i in range(n_nodes):
        for m in metrics:
            scfg = replace(cfg, metric=m)
            out.append(generate_stream(f"node{i:05d}.{m}", scfg, seed=seed))
    return out


@dataclass
class LogStream:
    """One synthetic log-line stream (ISSUE 9 log-template modality):
    raw lines + ground-truth anomaly windows. Feed ``lines`` through
    :class:`rtap_tpu.ingest.TemplateMiner` to get the template-id value
    stream a categorical composite field scores."""

    stream_id: str
    timestamps: np.ndarray  # int64 unix seconds, [T]
    lines: list[str]
    windows: list[tuple[int, int]] = field(default_factory=list)
    events: list[FaultEvent] = field(default_factory=list)


#: steady-state log-template pool: realistic shapes with numeric variable
#: positions (the drain-style miner masks digit-bearing tokens), one
#: format per template so mined ids are stable
_LOG_TEMPLATES = (
    "connected to host 10.0.{a}.{b} port {p}",
    "request /api/v1/items served in {ms} ms status 200",
    "heartbeat ok seq {n}",
    "cache lookup key item-{n} hit ratio 0.{r}",
    "gc pause {ms} ms heap {n} mb",
    "scheduled job sync-{n} finished rc 0",
)

#: the anomalous burst template — a structure steady state never emits
_LOG_BURST_TEMPLATE = "ERROR disk failure on volume {n} remounting read-only"


def generate_log_stream(
    stream_id: str, cfg: SyntheticStreamConfig, seed: int = 0,
) -> LogStream:
    """Seeded log-burst stream: one line per tick drawn from the steady
    template pool (numeric fields re-drawn per line, so the miner's
    masking is load-bearing), with ``cfg.n_anomalies`` bursts of the
    ERROR template injected post-probation — the log-burst workload of
    ROADMAP item 4. Windows label the burst spans NAB-style."""
    rng = _rng_for(seed, stream_id)
    T = cfg.length
    t_unix = (cfg.start_unix + np.arange(T) * cfg.cadence_s).astype(np.int64)
    # steady mix biased toward the first templates (realistic skew)
    weights = np.array([2.0 ** -i for i in range(len(_LOG_TEMPLATES))])
    weights /= weights.sum()
    choices = rng.choice(len(_LOG_TEMPLATES), size=T, p=weights)

    def render(i: int) -> str:
        return _LOG_TEMPLATES[choices[i]].format(
            a=rng.integers(256), b=rng.integers(256), p=rng.integers(1024, 65536),
            ms=rng.integers(1, 500), n=rng.integers(1, 100000),
            r=rng.integers(10, 99))

    lines = [render(i) for i in range(T)]
    windows: list[tuple[int, int]] = []
    events: list[FaultEvent] = []
    if cfg.n_anomalies > 0:
        lo = int(T * cfg.inject_after_frac)
        n_candidates = T - 50 - lo
        if n_candidates < cfg.n_anomalies:
            raise ValueError(
                f"stream length {T} too short for {cfg.n_anomalies} log "
                f"burst(s) past inject_after_frac={cfg.inject_after_frac}")
        centers = np.sort(rng.choice(np.arange(lo, T - 50),
                                     size=cfg.n_anomalies, replace=False))
        for c in centers:
            dur = int(rng.integers(5, 25))
            s, e = int(c), min(int(c) + dur, T - 1)
            for i in range(s, e):
                lines[i] = _LOG_BURST_TEMPLATE.format(n=rng.integers(16))
            margin = max(2, dur // 2)
            win = (int(t_unix[max(0, s - margin)]),
                   int(t_unix[min(T - 1, e + margin)]))
            windows.append(win)
            events.append(FaultEvent("log_burst", int(t_unix[s]),
                                     int(t_unix[e]), win))
    return LogStream(stream_id, t_unix, lines, windows, events)


def generate_categorical_stream(
    stream_id: str, cfg: SyntheticStreamConfig, seed: int = 0,
    n_classes: int = 6,
) -> LabeledStream:
    """Seeded event-class stream (ISSUE 9 categorical modality): each tick
    carries a category id drawn from a skewed steady distribution over
    ``n_classes`` classes; anomalies are bursts of a NOVEL class (id ==
    n_classes, never seen in steady state) — the shape a categorical
    encoder must catch and a scalar RDSE treats as merely 'one bucket
    further'. Values are float ids ready for a categorical field."""
    rng = _rng_for(seed, stream_id)
    T = cfg.length
    t_unix = (cfg.start_unix + np.arange(T) * cfg.cadence_s).astype(np.int64)
    weights = np.array([2.0 ** -i for i in range(n_classes)])
    weights /= weights.sum()
    values = rng.choice(n_classes, size=T, p=weights).astype(np.float32)
    windows: list[tuple[int, int]] = []
    events: list[FaultEvent] = []
    if cfg.n_anomalies > 0:
        lo = int(T * cfg.inject_after_frac)
        n_candidates = T - 50 - lo
        if n_candidates < cfg.n_anomalies:
            raise ValueError(
                f"stream length {T} too short for {cfg.n_anomalies} class "
                f"burst(s) past inject_after_frac={cfg.inject_after_frac}")
        centers = np.sort(rng.choice(np.arange(lo, T - 50),
                                     size=cfg.n_anomalies, replace=False))
        for c in centers:
            dur = int(rng.integers(5, 25))
            s, e = int(c), min(int(c) + dur, T - 1)
            values[s:e] = float(n_classes)  # the novel class
            margin = max(2, dur // 2)
            win = (int(t_unix[max(0, s - margin)]),
                   int(t_unix[min(T - 1, e + margin)]))
            windows.append(win)
            events.append(FaultEvent("class_burst", int(t_unix[s]),
                                     int(t_unix[e]), win))
    return LabeledStream(stream_id, t_unix, values, windows, events)


@dataclass
class TopologyWorkload:
    """A seeded multi-service cluster with ONE cascading fault: the
    correlation soak's ground truth (scripts/workload_soak.py,
    chaos_soak.py --topology-burst)."""

    streams: list[LabeledStream]
    #: the faulted service name
    burst_service: str
    #: nodes hit, in cascade order
    burst_nodes: list[str]
    #: tick index each node's burst begins (cascade: onset + j * lag)
    burst_onsets: dict[str, int]
    #: burst duration in ticks (per node)
    burst_dur: int
    #: the topology spec dict ({"services": ...}) matching the stream ids
    spec: dict
    #: origin node carrying the slow-drift precursor ramp (None: no ramp)
    precursor_node: str | None = None
    #: tick the origin node's ramp begins (its onset - precursor_ticks)
    precursor_start: int | None = None


def generate_topology_workload(
    n_services: int = 3,
    nodes_per_service: int = 3,
    metrics: Sequence[str] = ("cpu", "mem"),
    cfg: SyntheticStreamConfig | None = None,
    seed: int = 0,
    burst_at_frac: float = 0.75,
    cascade_lag: int = 2,
    burst_dur: int = 8,
    burst_magnitude: float = 12.0,
    precursor_ramp: float = 0.0,
    precursor_ticks: int = 0,
) -> TopologyWorkload:
    """Seeded cascading-fault workload (ISSUE 9 acceptance): per-node
    per-metric base signals (ids ``{svc}-{i:02d}.{metric}``, the
    inference-friendly naming), plus ONE deterministic multi-node burst —
    a seeded service is hit node by node (node j's burst begins
    ``cascade_lag * j`` ticks after the first) across ALL its metrics,
    the blast-radius shape exactly one cluster-level incident must
    cover. All other services stay fault-free (the false-positive
    control).

    ``precursor_ramp`` > 0 (with ``precursor_ticks`` > 0) prepends a
    slow linear drift to the ORIGIN node only — every metric climbs from
    0 to ``precursor_ramp * sigma`` over the ``precursor_ticks`` ticks
    ending at that node's burst onset (ISSUE 16's cascade scenario: the
    predictive horizon must page on the origin's drift BEFORE the second
    node's step fault lands). The ramp is applied post-draw like the
    burst itself, so enabling it never perturbs the RNG draw order: all
    other streams — and every stream of a ramp-free call — stay
    byte-identical to previous releases."""
    cfg = cfg or SyntheticStreamConfig(length=400, n_anomalies=0,
                                      noise_phi=0.9, noise_scale=0.3)
    if cfg.n_anomalies:
        raise ValueError(
            "generate_topology_workload owns its fault injection; pass a "
            "cfg with n_anomalies=0")
    if precursor_ramp < 0 or precursor_ticks < 0:
        raise ValueError("precursor_ramp/precursor_ticks must be >= 0")
    if (precursor_ramp > 0) != (precursor_ticks > 0):
        raise ValueError(
            "precursor_ramp and precursor_ticks arm the drift together: "
            "set both > 0 (or neither)")
    rng = _rng_for(seed, "topology-workload")
    svc_names = [f"svc{chr(ord('a') + i)}" for i in range(n_services)]
    burst_service = svc_names[int(rng.integers(n_services))]
    onset0 = int(cfg.length * burst_at_frac)
    if onset0 - precursor_ticks < 0:
        # same loud-failure discipline as the cascade-fit check below: a
        # truncated ramp would silently hand the eval a steeper (easier)
        # drift than the caller asked for
        raise ValueError(
            f"precursor ramp does not fit: onset {onset0} needs "
            f"{precursor_ticks} ramp ticks before it (lower "
            f"precursor_ticks or raise burst_at_frac/length)")
    last_onset = onset0 + cascade_lag * (nodes_per_service - 1)
    if last_onset + 2 > cfg.length - 1:
        # the last cascaded node must still get a real burst (>= 2 ticks
        # before the final tick) — fail loudly, like generate_log_stream,
        # instead of IndexError-ing on timestamps or silently emitting a
        # burst-less "burst node" that wrecks the soak's blast-radius check
        raise ValueError(
            f"cascade does not fit: last node's onset {last_onset} needs "
            f">= 2 burst ticks inside length {cfg.length} (lower "
            f"burst_at_frac/cascade_lag/nodes_per_service or raise length)")
    streams: list[LabeledStream] = []
    burst_nodes: list[str] = []
    burst_onsets: dict[str, int] = {}
    spec: dict = {"services": {}}
    for svc in svc_names:
        nodes = [f"{svc}-{i:02d}" for i in range(nodes_per_service)]
        spec["services"][svc] = nodes
        for j, node in enumerate(nodes):
            onset = onset0 + cascade_lag * j
            if svc == burst_service:
                burst_nodes.append(node)
                burst_onsets[node] = onset
            for m in metrics:
                scfg = replace(cfg, metric=m, n_anomalies=0)
                s = generate_stream(f"{node}.{m}", scfg, seed=seed)
                if svc == burst_service:
                    sigma = METRIC_PROFILES.get(
                        m, METRIC_PROFILES["cpu"])[2] * cfg.noise_scale
                    e = min(onset + burst_dur, cfg.length - 1)
                    sig = s.values.astype(np.float64)
                    sig[onset:e] += burst_magnitude * sigma
                    if precursor_ticks and j == 0:
                        # origin-node slow drift: 0 -> ramp*sigma over the
                        # ticks ending at onset (endpoint excluded — the
                        # step itself is the fault, the ramp its precursor)
                        r0 = onset - precursor_ticks
                        sig[r0:onset] += precursor_ramp * sigma * \
                            np.linspace(0.0, 1.0, precursor_ticks,
                                        endpoint=False)
                    lo_c, hi_c = METRIC_PROFILES.get(
                        m, METRIC_PROFILES["cpu"])[3]
                    if lo_c is not None:
                        sig = np.maximum(sig, lo_c)
                    if hi_c is not None:
                        sig = np.minimum(sig, hi_c)
                    s.values = sig.astype(np.float32)
                    margin = max(2, burst_dur // 2)
                    win = (int(s.timestamps[max(0, onset - margin)]),
                           int(s.timestamps[min(cfg.length - 1, e + margin)]))
                    s.windows.append(win)
                    s.events.append(FaultEvent(
                        "cascade", int(s.timestamps[onset]),
                        int(s.timestamps[e]), win))
                streams.append(s)
    return TopologyWorkload(
        streams=streams, burst_service=burst_service,
        burst_nodes=burst_nodes, burst_onsets=burst_onsets,
        burst_dur=burst_dur, spec=spec,
        precursor_node=burst_nodes[0] if precursor_ticks else None,
        precursor_start=(burst_onsets[burst_nodes[0]] - precursor_ticks)
        if precursor_ticks else None)


@dataclass
class NodeStream:
    """One node's fused multivariate stream (SURVEY.md §6 benchmark config 4:
    'multivariate per-node cpu/mem/net fused RDSE'): values [T, F] feed ONE
    HTM model with n_fields=F, versus `generate_cluster`'s one model per
    node-metric."""

    node_id: str
    metrics: tuple[str, ...]
    timestamps: np.ndarray  # int64 unix seconds, [T]
    values: np.ndarray  # float32, [T, F]
    windows: list[tuple[int, int]] = field(default_factory=list)
    events: list[FaultEvent] = field(default_factory=list)
    # which metric columns each event touched, index-aligned with `events`
    event_metrics: list[tuple[str, ...]] = field(default_factory=list)


def generate_node(
    node_id: str,
    cfg: SyntheticStreamConfig,
    metrics: Sequence[str] = ("cpu", "mem", "net"),
    seed: int = 0,
    coupled_frac: float = 0.5,
    fault_metrics: Sequence[str] | None = None,
) -> NodeStream:
    """Generate one node's multivariate stream with NODE-LEVEL faults.

    Each metric gets its own clean base signal (phase/noise keyed by
    `<node_id>.<metric>`, deterministic like everything else here). Faults
    are placed once per NODE at shared times — each event hits either ALL
    metrics simultaneously (probability `coupled_frac`: the node-saturation
    shape, e.g. cpu+mem+net degrade together) or exactly one metric (a
    single-metric fault the fused model must still catch). Windows are the
    union over touched metrics; `event_metrics` records the ground truth of
    which columns moved. `fault_metrics` restricts which metrics uncoupled
    faults may land on (evaluations use it to avoid metrics whose natural
    range makes a given fault kind in-distribution, e.g. a +6-sigma spike on
    `net`, whose diurnal peak already reaches that level).
    """
    if fault_metrics is not None:
        bad = set(fault_metrics) - set(metrics)
        if bad or not fault_metrics:
            raise ValueError(
                f"fault_metrics must be a non-empty subset of metrics {tuple(metrics)}; "
                f"got {tuple(fault_metrics)}"
            )
    n_anom = cfg.n_anomalies
    # A too-short stream makes the fault-center draw below degenerate (empty
    # or undersized candidate range -> opaque numpy ValueError); fail with
    # the actual constraint instead (ADVICE.md r3 — the CLI guards its own
    # replay path, but node_eval and other callers come through here).
    lo_check = int(cfg.length * cfg.inject_after_frac)
    n_candidates = cfg.length - 50 - lo_check
    if n_candidates < n_anom:
        raise ValueError(
            f"stream length {cfg.length} too short: the injection range "
            f"[{lo_check}, {cfg.length - 50}) has {max(n_candidates, 0)} candidate "
            f"centers for n_anomalies={n_anom}; lengthen the stream or lower "
            "inject_after_frac/n_anomalies"
        )
    cfg = replace(cfg, n_anomalies=0)  # per-metric injections off; node-level below
    parts = [
        generate_stream(f"{node_id}.{m}", replace(cfg, metric=m), seed=seed)
        for m in metrics
    ]
    values = np.stack([p.values for p in parts], axis=1)  # [T, F]
    t_unix = parts[0].timestamps
    rng = _rng_for(seed, node_id)

    windows: list[tuple[int, int]] = []
    events: list[FaultEvent] = []
    event_metrics: list[tuple[str, ...]] = []
    lo = int(cfg.length * cfg.inject_after_frac)
    centers = np.sort(rng.choice(np.arange(lo, cfg.length - 50), size=n_anom, replace=False))
    for c in centers:
        kind = cfg.kinds[rng.integers(len(cfg.kinds))]
        dur = int(rng.integers(5, 40))
        pool = tuple(fault_metrics) if fault_metrics is not None else tuple(metrics)
        if rng.random() < coupled_frac:
            touched = tuple(metrics)
        else:
            touched = (pool[rng.integers(len(pool))],)
        # the window is a function of (c, dur, margin) only, so every touched
        # metric of one event shares it — keep the first (win, ev) pair
        win = ev = None
        for f, m in enumerate(metrics):
            if m not in touched:
                continue
            sigma = METRIC_PROFILES.get(m, METRIC_PROFILES["cpu"])[2] * cfg.noise_scale
            col = np.ascontiguousarray(values[:, f], dtype=np.float64)
            w, e = _inject(col, t_unix, rng, replace(cfg, metric=m), sigma, kind, int(c), dur)
            win, ev = win or w, ev or e
            lo_c, hi_c = METRIC_PROFILES.get(m, METRIC_PROFILES["cpu"])[3]
            if lo_c is not None:
                col = np.maximum(col, lo_c)
            if hi_c is not None:
                col = np.minimum(col, hi_c)
            values[:, f] = col.astype(np.float32)
        windows.append(win)
        events.append(ev)
        event_metrics.append(touched)
    return NodeStream(node_id, tuple(metrics), t_unix, values, windows, events, event_metrics)
