from rtap_tpu.nab.scorer import (  # noqa: F401
    PROFILES,
    CostProfile,
    optimize_threshold,
    scaled_sigmoid,
    score_corpus,
    score_file,
)
