"""NAB (Numenta Anomaly Benchmark) scorer — reimplemented from the public spec.

The reference's headline quality metric is its NAB score (SURVEY.md §3.4,
§6); NAB itself could not be vendored offline, so this module reimplements
the published scoring algorithm (NAB paper "Evaluating Real-Time Anomaly
Detection Algorithms" + the nab/sweeper.py semantics described in SURVEY.md
C23):

- Each labeled anomaly has a window; the FIRST detection inside a window
  earns a true-positive credit weighted by a scaled sigmoid of its relative
  position (early detection -> credit near +1, at window end -> 0). Later
  detections inside the same window are ignored.
- A detection outside any window is a false positive: negative credit, -1.0
  if before any window, else a sigmoid decay based on distance from the
  preceding window's right edge (capped at -1 beyond 3 window-widths).
- A window with no detection is a false negative: costs fn_weight.
- Rows within the probationary period (15% of min(T, 5000)) are ignored.
- The corpus score uses ONE threshold optimized over the whole corpus, then
  is normalized 100 * (raw - null) / (perfect - null), where null = no
  detections and perfect = first-row-of-window detections with no FPs.

Weights per the three published profiles (standard / reward_low_FP /
reward_low_FN).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class CostProfile:
    name: str
    tp_weight: float
    fp_weight: float
    fn_weight: float


PROFILES = {
    "standard": CostProfile("standard", 1.0, 0.11, 1.0),
    "reward_low_FP": CostProfile("reward_low_FP", 1.0, 0.22, 1.0),
    "reward_low_FN": CostProfile("reward_low_FN", 1.0, 0.11, 2.0),
}

PROBATION_PERCENT = 0.15
PROBATION_CAP = 5000


def probation_rows(n_rows: int) -> int:
    return int(PROBATION_PERCENT * min(n_rows, PROBATION_CAP))


def scaled_sigmoid(rel_pos: np.ndarray | float) -> np.ndarray | float:
    """NAB's scaled sigmoid: +0.9866 at window start (-1), 0 at window end (0),
    decaying to -1 for positions after the window; flat -1 beyond rel_pos 3."""
    rel = np.asarray(rel_pos, dtype=np.float64)
    val = 2.0 / (1.0 + np.exp(5.0 * np.minimum(rel, 4.0))) - 1.0
    val = np.where(rel > 3.0, -1.0, val)
    return float(val) if np.isscalar(rel_pos) else val


def _window_indices(
    timestamps: np.ndarray, windows: list[tuple[int, int]]
) -> list[tuple[int, int]]:
    """Convert unix-second windows to [left_idx, right_idx] inclusive row spans."""
    out = []
    for a, b in windows:
        idx = np.nonzero((timestamps >= a) & (timestamps <= b))[0]
        if len(idx):
            out.append((int(idx[0]), int(idx[-1])))
    return out


def score_file(
    detections: np.ndarray,
    timestamps: np.ndarray,
    windows: list[tuple[int, int]],
    profile: CostProfile,
) -> float:
    """Raw NAB score of one file given binary detections per row."""
    spans = _window_indices(timestamps, windows)
    return _score_spans(detections, spans, profile)


def _score_spans(
    detections: np.ndarray, spans: list[tuple[int, int]], profile: CostProfile
) -> float:
    """Raw score given precomputed window row-spans (hot path of the sweep)."""
    n = len(detections)
    prob = probation_rows(n)
    det_idx = np.nonzero(detections)[0]
    det_idx = det_idx[det_idx >= prob]

    score = 0.0
    credited: set[int] = set()
    for i in det_idx:
        in_window = False
        for w_i, (l, r) in enumerate(spans):
            if l <= i <= r:
                in_window = True
                if w_i not in credited:
                    credited.add(w_i)
                    width = max(r - l, 1)
                    rel = (i - r) / width  # -1 at left edge, 0 at right edge
                    score += profile.tp_weight * scaled_sigmoid(rel)
                break
        if not in_window:
            # FP: sigmoid decay from preceding window's right edge; -1 before any
            prev = [(l, r) for (l, r) in spans if r < i]
            if prev:
                l, r = prev[-1]
                width = max(r - l, 1)
                rel = (i - r) / width  # > 0
                score += profile.fp_weight * scaled_sigmoid(rel)
            else:
                score += profile.fp_weight * -1.0
    # FNs
    score -= profile.fn_weight * (len(spans) - len(credited))
    return score


def _prepare(
    per_file: list[tuple[np.ndarray, np.ndarray, list[tuple[int, int]]]],
    profile: CostProfile,
) -> tuple[list[tuple[np.ndarray, list[tuple[int, int]]]], float, float]:
    """Precompute threshold-independent state: row spans + perfect/null totals."""
    prepped, perfect, null = [], 0.0, 0.0
    for scores, ts, windows in per_file:
        spans = _window_indices(ts, windows)
        prepped.append((scores, spans))
        perfect += profile.tp_weight * scaled_sigmoid(-1.0) * len(spans)
        null += -profile.fn_weight * len(spans)
    return prepped, perfect, null


def score_corpus(
    per_file: list[tuple[np.ndarray, np.ndarray, list[tuple[int, int]]]],
    threshold: float,
    profile: CostProfile,
) -> float:
    """Normalized corpus score (0-100 scale; null=0, perfect=100) at a fixed
    threshold. `per_file` entries are (anomaly_scores, timestamps, windows)."""
    prepped, perfect, null = _prepare(per_file, profile)
    if perfect == null:
        return 0.0
    raw = sum(_score_spans(s >= threshold, spans, profile) for s, spans in prepped)
    return 100.0 * (raw - null) / (perfect - null)


def optimize_threshold(
    per_file: list[tuple[np.ndarray, np.ndarray, list[tuple[int, int]]]],
    profile: CostProfile,
    max_candidates: int | None = None,
) -> tuple[float, float]:
    """EXHAUSTIVE threshold sweep over every distinct anomaly score (NAB's
    sweeper semantics) -> (best_threshold, best_normalized_score).

    Implemented as one descending-score incremental pass, O(n log n) over
    the pooled corpus instead of O(n) full re-scores per candidate: walking
    thresholds downward only ever ADDS detections, so each row contributes
    a precomputable delta — an FP row its (static) sigmoid cost, a window
    row an upgrade of its window's credit (windows never overlap in NAB,
    so the earliest active row in a window is also the max-credit one, and
    a window's first activation also cancels its FN cost). Equivalence
    with the direct per-threshold scorer is property-tested against
    `score_corpus` on randomized corpora
    (tests/unit/test_nab_scorer_examples.py).

    `max_candidates` is accepted for backward compatibility and ignored:
    the sweep is always exhaustive (the r4 verdict flagged the previous
    ~200-quantile approximation as silent scoring drift vs NAB).
    """
    del max_candidates
    prepped, perfect, null = _prepare(per_file, profile)
    n_windows = sum(len(spans) for _, spans in prepped)

    # flatten: for each post-probation row, (score, window_key or None,
    # contribution). Window rows carry their credit; FP rows their cost.
    rows: list[tuple[float, int, float]] = []  # (score, kind/window id, value)
    FP = -1  # kind marker for non-window rows
    wid = 0
    for scores, spans in prepped:
        prob = probation_rows(len(scores))
        file_wids = list(range(wid, wid + len(spans)))
        wid += len(spans)
        # NaN scores can never satisfy `score >= t` in the direct scorer,
        # so they are excluded from the walk the same way
        for i in np.nonzero(~np.isnan(scores))[0]:
            if i < prob:
                continue
            placed = False
            for w_local, (l, r) in enumerate(spans):
                if l <= i <= r:
                    width = max(r - l, 1)
                    credit = profile.tp_weight * scaled_sigmoid((i - r) / width)
                    rows.append((float(scores[i]), file_wids[w_local], credit))
                    placed = True
                    break
            if not placed:
                prev = [(l, r) for (l, r) in spans if r < i]
                if prev:
                    l, r = prev[-1]
                    width = max(r - l, 1)
                    cost = profile.fp_weight * scaled_sigmoid((i - r) / width)
                else:
                    cost = -profile.fp_weight
                rows.append((float(scores[i]), FP, cost))

    if perfect == null:
        return 1.1, 0.0

    def normalize(raw: float) -> float:
        return 100.0 * (raw - null) / (perfect - null)

    # descending-score walk; snapshot after each distinct score value
    rows.sort(key=lambda t: -t[0])
    running = -profile.fn_weight * n_windows  # nothing detected
    best_t, best_s = 1.1, normalize(running)
    window_credit: dict[int, float] = {}
    i = 0
    while i < len(rows):
        v = rows[i][0]
        while i < len(rows) and rows[i][0] == v:
            _, kind, val = rows[i]
            if kind == FP:
                running += val
            elif kind not in window_credit:
                window_credit[kind] = val
                running += profile.fn_weight + val  # cancel FN, add credit
            elif val > window_credit[kind]:
                running += val - window_credit[kind]
                window_credit[kind] = val
            i += 1
        s = normalize(running)
        if s > best_s:
            best_t, best_s = v, s
    return best_t, best_s
