"""NAB (Numenta Anomaly Benchmark) scorer — reimplemented from the public spec.

The reference's headline quality metric is its NAB score (SURVEY.md §3.4,
§6); NAB itself could not be vendored offline, so this module reimplements
the published scoring algorithm (NAB paper "Evaluating Real-Time Anomaly
Detection Algorithms" + the nab/sweeper.py semantics described in SURVEY.md
C23):

- Each labeled anomaly has a window; the FIRST detection inside a window
  earns a true-positive credit weighted by a scaled sigmoid of its relative
  position (early detection -> credit near +1, at window end -> 0). Later
  detections inside the same window are ignored.
- A detection outside any window is a false positive: negative credit, -1.0
  if before any window, else a sigmoid decay based on distance from the
  preceding window's right edge (capped at -1 beyond 3 window-widths).
- A window with no detection is a false negative: costs fn_weight.
- Rows within the probationary period (15% of min(T, 5000)) are ignored.
- The corpus score uses ONE threshold optimized over the whole corpus, then
  is normalized 100 * (raw - null) / (perfect - null), where null = no
  detections and perfect = first-row-of-window detections with no FPs.

Weights per the three published profiles (standard / reward_low_FP /
reward_low_FN).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class CostProfile:
    name: str
    tp_weight: float
    fp_weight: float
    fn_weight: float


PROFILES = {
    "standard": CostProfile("standard", 1.0, 0.11, 1.0),
    "reward_low_FP": CostProfile("reward_low_FP", 1.0, 0.22, 1.0),
    "reward_low_FN": CostProfile("reward_low_FN", 1.0, 0.11, 2.0),
}

PROBATION_PERCENT = 0.15
PROBATION_CAP = 5000


def probation_rows(n_rows: int) -> int:
    return int(PROBATION_PERCENT * min(n_rows, PROBATION_CAP))


def scaled_sigmoid(rel_pos: np.ndarray | float) -> np.ndarray | float:
    """NAB's scaled sigmoid: +0.9866 at window start (-1), 0 at window end (0),
    decaying to -1 for positions after the window; flat -1 beyond rel_pos 3."""
    rel = np.asarray(rel_pos, dtype=np.float64)
    val = 2.0 / (1.0 + np.exp(5.0 * np.minimum(rel, 4.0))) - 1.0
    val = np.where(rel > 3.0, -1.0, val)
    return float(val) if np.isscalar(rel_pos) else val


def _window_indices(
    timestamps: np.ndarray, windows: list[tuple[int, int]]
) -> list[tuple[int, int]]:
    """Convert unix-second windows to [left_idx, right_idx] inclusive row spans."""
    out = []
    for a, b in windows:
        idx = np.nonzero((timestamps >= a) & (timestamps <= b))[0]
        if len(idx):
            out.append((int(idx[0]), int(idx[-1])))
    return out


def score_file(
    detections: np.ndarray,
    timestamps: np.ndarray,
    windows: list[tuple[int, int]],
    profile: CostProfile,
) -> float:
    """Raw NAB score of one file given binary detections per row."""
    spans = _window_indices(timestamps, windows)
    return _score_spans(detections, spans, profile)


def _score_spans(
    detections: np.ndarray, spans: list[tuple[int, int]], profile: CostProfile
) -> float:
    """Raw score given precomputed window row-spans (hot path of the sweep)."""
    n = len(detections)
    prob = probation_rows(n)
    det_idx = np.nonzero(detections)[0]
    det_idx = det_idx[det_idx >= prob]

    score = 0.0
    credited: set[int] = set()
    for i in det_idx:
        in_window = False
        for w_i, (l, r) in enumerate(spans):
            if l <= i <= r:
                in_window = True
                if w_i not in credited:
                    credited.add(w_i)
                    width = max(r - l, 1)
                    rel = (i - r) / width  # -1 at left edge, 0 at right edge
                    score += profile.tp_weight * scaled_sigmoid(rel)
                break
        if not in_window:
            # FP: sigmoid decay from preceding window's right edge; -1 before any
            prev = [(l, r) for (l, r) in spans if r < i]
            if prev:
                l, r = prev[-1]
                width = max(r - l, 1)
                rel = (i - r) / width  # > 0
                score += profile.fp_weight * scaled_sigmoid(rel)
            else:
                score += profile.fp_weight * -1.0
    # FNs
    score -= profile.fn_weight * (len(spans) - len(credited))
    return score


def _prepare(
    per_file: list[tuple[np.ndarray, np.ndarray, list[tuple[int, int]]]],
    profile: CostProfile,
) -> tuple[list[tuple[np.ndarray, list[tuple[int, int]]]], float, float]:
    """Precompute threshold-independent state: row spans + perfect/null totals."""
    prepped, perfect, null = [], 0.0, 0.0
    for scores, ts, windows in per_file:
        spans = _window_indices(ts, windows)
        prepped.append((scores, spans))
        perfect += profile.tp_weight * scaled_sigmoid(-1.0) * len(spans)
        null += -profile.fn_weight * len(spans)
    return prepped, perfect, null


def score_corpus(
    per_file: list[tuple[np.ndarray, np.ndarray, list[tuple[int, int]]]],
    threshold: float,
    profile: CostProfile,
) -> float:
    """Normalized corpus score (0-100 scale; null=0, perfect=100) at a fixed
    threshold. `per_file` entries are (anomaly_scores, timestamps, windows)."""
    prepped, perfect, null = _prepare(per_file, profile)
    if perfect == null:
        return 0.0
    raw = sum(_score_spans(s >= threshold, spans, profile) for s, spans in prepped)
    return 100.0 * (raw - null) / (perfect - null)


def optimize_threshold(
    per_file: list[tuple[np.ndarray, np.ndarray, list[tuple[int, int]]]],
    profile: CostProfile,
    max_candidates: int = 200,
) -> tuple[float, float]:
    """Sweep candidate thresholds (quantiles of the pooled score distribution,
    as in NAB's exhaustive sweeper) -> (best_threshold, best_normalized_score)."""
    pooled = np.concatenate([s for s, _, _ in per_file]) if per_file else np.array([0.5])
    qs = np.unique(np.quantile(pooled, np.linspace(0.0, 1.0, max_candidates)))
    candidates = np.unique(np.concatenate([qs, [0.5, 0.9, 0.99, 1.0, 1.1]]))
    prepped, perfect, null = _prepare(per_file, profile)
    best_t, best_s = 1.1, -np.inf
    for t in candidates:
        if perfect == null:
            s = 0.0
        else:
            raw = sum(_score_spans(sc >= t, spans, profile) for sc, spans in prepped)
            s = 100.0 * (raw - null) / (perfect - null)
        if s > best_s:
            best_t, best_s = float(t), s
    return best_t, best_s
