"""NAB corpus runner: detector over every file -> optimized corpus scores.

Analog of NAB's `run.py --detect --score --normalize` (SURVEY.md §3.4): one
fresh detector per corpus file (sized to that file's value range, as NAB
does), raw detection scores collected per row, then a single corpus-wide
threshold sweep per cost profile. The reference parallelizes with one
process per file (multiprocessing, SURVEY.md §2.3); we expose the same
option for the CPU backend, while the TPU backend instead batches files
into one vmapped stream group (service/registry.py).
"""

from __future__ import annotations

import multiprocessing as mp
from dataclasses import dataclass

import numpy as np

from rtap_tpu.config import ModelConfig, nab_preset, rdse_resolution
from rtap_tpu.data.nab_corpus import NabFile
from rtap_tpu.models.htm_model import AnomalyDetector
from rtap_tpu.nab.scorer import PROFILES, optimize_threshold


@dataclass
class NabRunResult:
    scores: dict[str, tuple[float, float]]  # profile -> (best_threshold, score)
    per_file: list[tuple[np.ndarray, np.ndarray, list[tuple[int, int]]]]


def _file_range_config(nf: NabFile, base_cfg: ModelConfig | None) -> ModelConfig:
    lo, hi = float(nf.values.min()), float(nf.values.max())
    if base_cfg is None:
        return nab_preset(lo, hi)
    # rescale only the encoder resolution to this file's range, NAB-style
    import dataclasses

    res = rdse_resolution(lo, hi)
    return dataclasses.replace(base_cfg, rdse=dataclasses.replace(base_cfg.rdse, resolution=res))


def detect_file(
    nf: NabFile, cfg: ModelConfig | None = None, backend: str = "cpu", seed: int = 0
) -> np.ndarray:
    """Run one detector over one file -> detection scores (log-likelihood)."""
    det = AnomalyDetector(_file_range_config(nf, cfg), backend=backend, seed=seed)
    out = np.zeros(len(nf.values), np.float64)
    for i, (t, v) in enumerate(zip(nf.timestamps, nf.values)):
        out[i], _ = det.handle_record(int(t), float(v))
    return out


def _detect_star(args):
    return detect_file(*args)


def run_corpus(
    files: list[NabFile],
    cfg: ModelConfig | None = None,
    backend: str = "cpu",
    seed: int = 0,
    processes: int = 1,
    profiles: tuple[str, ...] = ("standard", "reward_low_FP", "reward_low_FN"),
) -> NabRunResult:
    """Detect + score + normalize over a corpus (NAB run.py analog)."""
    if processes > 1 and backend == "cpu":
        with mp.get_context("spawn").Pool(processes) as pool:
            scores = pool.map(_detect_star, [(nf, cfg, backend, seed) for nf in files])
    else:
        scores = [detect_file(nf, cfg, backend, seed) for nf in files]
    per_file = [(s, nf.timestamps, nf.windows) for s, nf in zip(scores, files)]
    results = {p: optimize_threshold(per_file, PROFILES[p]) for p in profiles}
    return NabRunResult(results, per_file)
