"""NAB corpus runner: detector over every file -> optimized corpus scores.

Analog of NAB's `run.py --detect --score --normalize` (SURVEY.md §3.4): one
fresh detector per corpus file (sized to that file's value range, as NAB
does), raw detection scores collected per row, then a single corpus-wide
threshold sweep per cost profile. The reference parallelizes with one
process per file (multiprocessing, SURVEY.md §2.3); we expose the same
option for the CPU backend, while the TPU backend instead batches files
into one vmapped stream group (service/registry.py).
"""

from __future__ import annotations

import multiprocessing as mp
from dataclasses import dataclass

import numpy as np

from rtap_tpu.config import ModelConfig, nab_preset, rdse_resolution
from rtap_tpu.data.nab_corpus import NabFile
from rtap_tpu.models.htm_model import AnomalyDetector
from rtap_tpu.nab.scorer import PROFILES, optimize_threshold


@dataclass
class NabRunResult:
    scores: dict[str, tuple[float, float]]  # profile -> (best_threshold, score)
    per_file: list[tuple[np.ndarray, np.ndarray, list[tuple[int, int]]]]


def _file_range_config(nf: NabFile, base_cfg: ModelConfig | None) -> ModelConfig:
    # nan-aware: a missing sample (NaN value) must not poison the encoder
    # resolution (min() would return NaN); detect_files_batched sizes with
    # the same nan-aware range so both paths stay score-identical
    lo, hi = float(np.nanmin(nf.values)), float(np.nanmax(nf.values))
    if base_cfg is None:
        return nab_preset(lo, hi)
    # rescale only the encoder resolution to this file's range, NAB-style
    import dataclasses

    res = rdse_resolution(lo, hi)
    return dataclasses.replace(base_cfg, rdse=dataclasses.replace(base_cfg.rdse, resolution=res))


def detect_file(
    nf: NabFile, cfg: ModelConfig | None = None, backend: str = "cpu", seed: int = 0
) -> np.ndarray:
    """Run one detector over one file -> detection scores (log-likelihood)."""
    det = AnomalyDetector(_file_range_config(nf, cfg), backend=backend, seed=seed)
    out = np.zeros(len(nf.values), np.float64)
    for i, (t, v) in enumerate(zip(nf.timestamps, nf.values)):
        out[i], _ = det.handle_record(int(t), float(v))
    return out


def detect_files_batched(
    files: list[NabFile],
    cfg: ModelConfig | None = None,
    seed: int = 0,
    chunk_ticks: int = 64,
) -> list[np.ndarray]:
    """Benchmark config 2's real shape (SURVEY.md §6): every corpus file as
    one stream of ONE vmapped device group — a chunk of ticks for the whole
    corpus costs a single dispatch, vs one Python-loop record at a time per
    file.

    NAB's per-file encoder sizing survives batching because the RDSE
    resolution is runtime state, not program structure (models/state.py
    `enc_resolution`): one compiled program serves files with different
    value ranges. Files shorter than the longest pad with NaN values (the
    encoder's missing-sample path) on a continued cadence; padded rows are
    sliced off the returned scores. Same per-file scores as `detect_file`
    modulo backend rounding (exact on the CPU test platform —
    tests/integration/test_nab_run.py pins it).
    """
    import jax.numpy as jnp

    from rtap_tpu.config import nab_preset
    from rtap_tpu.service.registry import StreamGroup

    n = len(files)
    T = max(len(f.values) for f in files)
    base = cfg if cfg is not None else nab_preset(0.0, 100.0)
    grp = StreamGroup(base, [f.name for f in files], seed=seed, backend="tpu")
    res = np.array(
        [rdse_resolution(float(np.nanmin(f.values)), float(np.nanmax(f.values)))
         for f in files], np.float32,
    )[:, None].repeat(base.n_fields, axis=1)  # [G, n_fields]
    grp.state = {**grp.state, "enc_resolution": jnp.asarray(res)}

    vals = np.full((T, n), np.nan, np.float32)
    ts = np.zeros((T, n), np.int64)
    for g, f in enumerate(files):
        L = len(f.values)
        vals[:L, g] = f.values
        ts[:L, g] = f.timestamps
        if L < T:  # continue the file's cadence so the date encoder stays sane
            step = int(np.median(np.diff(f.timestamps))) if L > 1 else 1
            ts[L:, g] = f.timestamps[-1] + np.arange(1, T - L + 1) * max(step, 1)

    loglik = np.empty((T, n))
    for t0 in range(0, T, chunk_ticks):
        t1 = min(t0 + chunk_ticks, T)
        _, ll, _ = grp.run_chunk(vals[t0:t1], ts[t0:t1])
        loglik[t0:t1] = ll
    return [loglik[: len(f.values), g] for g, f in enumerate(files)]


def _detect_star(args):
    return detect_file(*args)


def run_corpus(
    files: list[NabFile],
    cfg: ModelConfig | None = None,
    backend: str = "cpu",
    seed: int = 0,
    processes: int = 1,
    profiles: tuple[str, ...] = ("standard", "reward_low_FP", "reward_low_FN"),
) -> NabRunResult:
    """Detect + score + normalize over a corpus (NAB run.py analog).

    backend="cpu": one oracle detector per file (optionally one process per
    file, the reference's parallelism). backend="tpu": all files batched
    into one vmapped device group (:func:`detect_files_batched`).
    """
    if backend == "tpu":
        scores = detect_files_batched(files, cfg, seed)
    elif processes > 1:
        with mp.get_context("spawn").Pool(processes) as pool:
            scores = pool.map(_detect_star, [(nf, cfg, backend, seed) for nf in files])
    else:
        scores = [detect_file(nf, cfg, backend, seed) for nf in files]
    per_file = [(s, nf.timestamps, nf.windows) for s, nf in zip(scores, files)]
    results = {p: optimize_threshold(per_file, PROFILES[p]) for p in profiles}
    return NabRunResult(results, per_file)
